"""Pod-scale index sharding: partition one index across a named mesh axis.

``ReplicaGroup`` scales *throughput* by replicating the whole index and
sharding queries; capacity stays capped by a single chip's HBM.  This
module scales *capacity*: :class:`ShardedIndex` partitions the index
itself — brute-force rows, IVF lists (ivf_flat + ivf_pq; CAGRA falls back
to row-partitioned brute refine over its dataset) — across the devices of
a :class:`~raft_tpu.comms.comms.Comms` mesh axis via ``NamedSharding``,
so each device holds ~1/N of the index.

Search is the blocking scheme of "Large Scale Distributed Linear Algebra
With TPUs" (PAPERS.md) applied to ANN: every shard runs the *existing*
local search (the same dispatch the single-device path uses, including
the Pallas IVF scan legs) over its partition under ``shard_map``, then the
global answer is produced by one cross-shard merge — an all-gather of the
per-shard top-k candidates followed by a single tie-stable
:func:`~raft_tpu.ops.matrix.select_k_stable`.  The merge collective moves
``n_shards · k`` candidates per query (tiny next to the index), and an
optional bf16 cast on the gathered distances (EQuARX-style,
``RAFT_TPU_SHARD_MERGE_DTYPE=bfloat16``) halves even that — candidate
distances tolerate low precision before any final refine.

Semantics vs the single-device backends:

- ``brute_force`` / ``cagra`` fallback: exact — the per-shard candidate
  union always contains the global top-k, and the id-tie-stable merge
  returns identical (ids, distances).
- ``ivf_flat`` / ``ivf_pq``: each shard probes up to ``n_probes`` of *its
  own* lists, so the probed set is a superset of the single-device probed
  set — recall is ≥ the unsharded search at equal ``n_probes`` (exactly
  equal when probing is exhaustive, ``n_probes >= n_lists``).  This
  mirrors how multi-GPU IVF deployments shard (per-partition probing).

Tombstones from a :class:`~raft_tpu.serve.mutation.MutableIndex` are
folded in at shard time (the global pass bitset is tiny and rides along
replicated); live side-buffer rows are rejected — compact/rebuild before
sharding.  A sharded index is an immutable serving layout: mutate the
source and hot-swap a fresh :meth:`ShardedIndex.from_index` through the
registry.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu import obs
from raft_tpu import kernels as _kernels
from raft_tpu.comms.comms import Comms, local_comms
from raft_tpu.core import env as _env
from raft_tpu.core.bitset import Bitset, RowFilter, WORD_BITS
from raft_tpu.core.compat import shard_map
from raft_tpu.core.trace import trace_range
from raft_tpu.distance.pairwise import DISTANCE_TYPES
from raft_tpu.ops import matrix
from raft_tpu.serve.mutation import MutableIndex

#: env knob for the merge all-gather's distance dtype (EQuARX-style
#: quantized collective): "float32" (default, exact) or "bfloat16"
MERGE_DTYPE_ENV = "RAFT_TPU_SHARD_MERGE_DTYPE"

_MERGE_DTYPES = {
    "float32": None,  # no cast — gather full-precision distances
    "f32": None,
    "bfloat16": jnp.bfloat16,
    "bf16": jnp.bfloat16,
}


def merge_dtype_from_env() -> Optional[jnp.dtype]:
    """Resolve ``RAFT_TPU_SHARD_MERGE_DTYPE`` to a cast dtype (or None)."""
    name = _env.env_str(MERGE_DTYPE_ENV, "float32").strip().lower()
    if name not in _MERGE_DTYPES:
        raise ValueError(
            f"{MERGE_DTYPE_ENV}={name!r} not understood; expected one of "
            f"{sorted(_MERGE_DTYPES)}"
        )
    return _MERGE_DTYPES[name]


def _pack_pass_words(mask: np.ndarray) -> np.ndarray:
    """Pack a boolean pass mask into Bitset-layout uint32 words (host)."""
    n = mask.shape[0]
    nw = (n + WORD_BITS - 1) // WORD_BITS
    padded = np.zeros((nw * WORD_BITS,), np.uint32)
    padded[:n] = mask.astype(np.uint32)
    shifts = np.arange(WORD_BITS, dtype=np.uint32)
    return np.sum(
        padded.reshape(nw, WORD_BITS) << shifts[None, :], axis=1, dtype=np.uint32
    )


#: env knob for how sharded CAGRA serves: "brute" (row-partitioned brute
#: refine, exact) or "graph" (partitioned graph traversal, graph_shard.py)
CAGRA_MODE_ENV = "RAFT_TPU_SHARD_CAGRA"


def _resolve_cagra_mode(mode: str) -> str:
    if mode == "env":
        mode = (_env.env_str(CAGRA_MODE_ENV, "brute") or "brute")
    mode = mode.strip().lower()
    if mode not in ("brute", "graph"):
        raise ValueError(
            f"cagra shard mode {mode!r} not understood; expected 'brute', "
            f"'graph' or 'env' ({CAGRA_MODE_ENV})"
        )
    return mode


def _round_robin(n_items: int, n_shards: int) -> list:
    """Per-shard item indices, round-robin (balances size-sorted skew)."""
    return [np.arange(s, n_items, n_shards) for s in range(n_shards)]


class ShardedIndex:
    """One logical index partitioned across a mesh axis.

    Build via :meth:`from_index`; call :meth:`search` like any backend.
    Quacks enough like :class:`MutableIndex` (``kind``/``dim``/``size``/
    ``generation``/``pending_mutations``/``device_bytes``/``search``) to be
    registered and hot-swapped through ``IndexRegistry``/``SearchService``
    and served by ``ReplicaGroup``/``MicroBatcher``.
    """

    #: True on the partitioned-graph CAGRA subclass
    #: (:class:`raft_tpu.serve.graph_shard.GraphShardedIndex`) — consumers
    #: (kernel-path stamps, explain) read it duck-typed via ``getattr``
    graph_mode = False

    def __init__(
        self,
        comms: Comms,
        kind: str,
        metric: str,
        dim: int,
        size: int,
        parts: Dict[str, jax.Array],
        specs: Dict[str, P],
        *,
        search_params=None,
        merge_dtype=None,
        label: str = "",
        shard_stats: Optional[Dict[str, list]] = None,
    ):
        self.comms = comms
        self.kind = kind
        self.metric = metric
        self.dim = int(dim)
        self.size = int(size)
        self.search_params = search_params
        self.label = label or kind
        self.merge_dtype = merge_dtype
        canonical = DISTANCE_TYPES[metric]
        self.select_min = canonical != "inner_product"
        self._names = tuple(parts)
        self._parts = parts
        self._specs = specs
        self._searchers: Dict[Tuple[int, ...], object] = {}
        # MutableIndex-compatible serving surface: a sharded layout is
        # immutable — mutate the source index and hot-swap a re-shard
        self.generation = 0
        self._shard_stats = shard_stats or {}
        self._publish_shard_gauges()

    # -- construction --------------------------------------------------------
    @classmethod
    def from_index(
        cls,
        index,
        comms: Optional[Comms] = None,
        *,
        n_devices: Optional[int] = None,
        search_params=None,
        merge_dtype="env",
        label: str = "",
        cagra_mode: str = "env",
    ) -> "ShardedIndex":
        """Partition a built index (or a compacted ``MutableIndex``) across
        ``comms``'s axis.

        ``merge_dtype`` defaults to the ``RAFT_TPU_SHARD_MERGE_DTYPE`` env
        knob; pass ``None`` (exact f32 merge) or ``jnp.bfloat16`` to
        override.  A ``MutableIndex`` may carry tombstones (folded into the
        sharded filter) but not live side-buffer rows.

        ``cagra_mode`` selects how a CAGRA index is served: ``"brute"``
        (default; row-partitioned brute refine — exact, the correctness
        control arm), ``"graph"`` (partitioned graph traversal with halo
        frontiers, :mod:`raft_tpu.serve.graph_shard`), or ``"env"`` to
        consult ``RAFT_TPU_SHARD_CAGRA``.
        """
        comms = comms if comms is not None else local_comms(n_devices)
        if merge_dtype == "env":
            merge_dtype = merge_dtype_from_env()
        deleted = None
        if isinstance(index, MutableIndex):
            with index._lock:
                if int(index._side_live.sum()) > 0:
                    raise ValueError(
                        "cannot shard a MutableIndex with live side-buffer "
                        "rows; rebuild/compact the index first"
                    )
                if index._main_ids is not None:
                    # the sharded layouts carry global ids as row positions
                    # (arange rows / list_index); a compacted id map would
                    # silently serve wrong ids through them
                    raise ValueError(
                        "cannot shard a MutableIndex with a remapped id "
                        "space (a compacted index); rebuild it with dense "
                        "ids from live_vectors() first"
                    )
                if index._n_deleted:
                    deleted = index._deleted.copy()
            if search_params is None:
                search_params = index.search_params
            kind, inner = index.kind, index.index
        else:
            kind, inner = _infer_kind(index), index
        if kind == "cagra" and _resolve_cagra_mode(cagra_mode) == "graph":
            from raft_tpu.serve.graph_shard import GraphShardedIndex

            return GraphShardedIndex._shard_graph(
                comms, inner, deleted, search_params, merge_dtype, label
            )
        if kind in ("brute_force", "cagra"):
            # CAGRA's graph is a per-shard traversal structure with global
            # fan-out; the default CAGRA mode therefore serves the capacity
            # win by sharding the rows — row-partitioned brute refine over
            # its dataset (exact; the graph mode's correctness control arm)
            return cls._shard_rows(
                comms, kind, inner, deleted, merge_dtype, label
            )
        if kind == "ivf_flat":
            return cls._shard_ivf_flat(
                comms, inner, deleted, search_params, merge_dtype, label
            )
        if kind == "ivf_pq":
            return cls._shard_ivf_pq(
                comms, inner, deleted, search_params, merge_dtype, label
            )
        raise ValueError(f"unsupported index kind for sharding: {kind!r}")

    @classmethod
    def _shard_rows(cls, comms, kind, inner, deleted, merge_dtype, label):
        data = np.asarray(inner.dataset)
        n, d = data.shape
        s_count = comms.get_size()
        r = -(-n // s_count)
        rows = np.zeros((s_count, r, d), data.dtype)
        ids = np.full((s_count, r), -1, np.int32)
        words = np.zeros(
            (s_count, (r + WORD_BITS - 1) // WORD_BITS), np.uint32
        )
        row_counts = []
        for s in range(s_count):
            lo, hi = s * r, min((s + 1) * r, n)
            m = hi - lo
            if m > 0:
                rows[s, :m] = data[lo:hi]
                ids[s, :m] = np.arange(lo, hi, dtype=np.int32)
            passes = np.zeros((r,), bool)
            passes[:m] = True
            if deleted is not None and m > 0:
                passes[:m] &= ~deleted[lo:hi]
            words[s] = _pack_pass_words(passes)
            row_counts.append(int(passes.sum()))
        parts, specs = _place(
            comms,
            sharded={"rows": rows, "ids": ids, "pass_words": words},
            replicated={},
        )
        live = n if deleted is None else n - int(deleted.sum())
        return cls(
            comms, kind, inner.metric, d, live, parts, specs,
            merge_dtype=merge_dtype, label=label,
            shard_stats={"rows": row_counts},
        )

    @classmethod
    def _shard_ivf_flat(cls, comms, inner, deleted, params, merge_dtype, label):
        from raft_tpu.neighbors import ivf_flat

        params = params if params is not None else ivf_flat.SearchParams()
        arrays = {
            "centers": np.asarray(inner.centers),
            "list_data": np.asarray(inner.list_data),
            "list_index": np.asarray(inner.list_index),
            "list_sizes": np.asarray(inner.list_sizes),
            "list_norms": np.asarray(inner.list_norms),
        }
        fills = {"list_index": -1, "list_sizes": 0, "list_norms": np.inf}
        sharded, stats = _partition_lists(arrays, fills, comms.get_size())
        n_main = int(arrays["list_sizes"].sum())
        replicated = _global_pass_filter(deleted, n_main)
        parts, specs = _place(comms, sharded=sharded, replicated=replicated)
        live = n_main if deleted is None else n_main - int(deleted.sum())
        return cls(
            comms, "ivf_flat", inner.metric, int(inner.dim), live, parts,
            specs, search_params=params, merge_dtype=merge_dtype, label=label,
            shard_stats=stats,
        )

    @classmethod
    def _shard_ivf_pq(cls, comms, inner, deleted, params, merge_dtype, label):
        from raft_tpu.neighbors import ivf_pq

        params = params if params is not None else ivf_pq.SearchParams()
        arrays = {
            "centers": np.asarray(inner.centers),
            "centers_rot": np.asarray(inner.centers_rot),
            "list_codes": np.asarray(inner.list_codes),
            "list_index": np.asarray(inner.list_index),
            "list_sizes": np.asarray(inner.list_sizes),
            "list_data": np.asarray(inner.list_data),
            "list_y2": np.asarray(inner.list_y2),
        }
        fills = {"list_index": -1, "list_sizes": 0, "list_y2": np.inf}
        replicated = {"rotation": np.asarray(inner.rotation)}
        if inner.codebook_kind == "per_cluster":
            arrays["codebook"] = np.asarray(inner.codebook)
        else:
            replicated["codebook"] = np.asarray(inner.codebook)
        sharded, stats = _partition_lists(arrays, fills, comms.get_size())
        n_main = int(arrays["list_sizes"].sum())
        replicated.update(_global_pass_filter(deleted, n_main))
        parts, specs = _place(comms, sharded=sharded, replicated=replicated)
        live = n_main if deleted is None else n_main - int(deleted.sum())
        self = cls(
            comms, "ivf_pq", inner.metric, int(inner.dim), live, parts,
            specs, search_params=params, merge_dtype=merge_dtype, label=label,
            shard_stats=stats,
        )
        self._pq_meta = (
            inner.codebook_kind, int(inner.pq_bits), float(inner.scan_scale),
        )
        return self

    # -- search --------------------------------------------------------------
    def search(
        self, queries, k: int, *, sample_filter=None
    ) -> Tuple[jax.Array, jax.Array]:
        """Global (distances [q, k], ids [q, k]) over all shards.

        One SPMD dispatch: per-shard local search + the single cross-shard
        merge collective.  Executables are cached per k (and per query
        batch shape via jit), preserving the batcher's zero-recompile
        contract once the bucket ladder is warm.

        ``sample_filter`` is an optional per-query
        :class:`~raft_tpu.core.bitset.RowFilter` over **global** ids (the
        ragged path's packed predicate words, replicated to every shard):
        the IVF legs pass it straight into the local search (their
        ``list_index`` ids are global), the row-partitioned legs re-base
        the global bits onto each shard's local rows.  The filtered
        executable is cached separately — serving a filter-free stream
        never pays the gather.
        """
        queries = jnp.asarray(queries, jnp.float32)
        if queries.ndim != 2 or queries.shape[1] != self.dim:
            raise ValueError(
                f"queries shape {queries.shape} vs index dim {self.dim}"
            )
        fargs = ()
        filter_bits = None
        if sample_filter is not None:
            if not isinstance(sample_filter, RowFilter):
                raise TypeError(
                    "ShardedIndex.search expects a per-query RowFilter "
                    f"over global ids, got {type(sample_filter).__name__}"
                )
            filter_bits = int(sample_filter.n_bits)
            fargs = (jnp.asarray(sample_filter.words, jnp.uint32),)
        f = self._searcher(int(k), filter_bits)
        t0 = time.perf_counter()
        with trace_range("serve.sharded_search") as sp:
            v, i = f(queries, *fargs, *(self._parts[n] for n in self._names))
            dt = time.perf_counter() - t0
            if sp is not None:
                # dispatch: tracing/enqueue of the sharded executable (the
                # device wait lands in the caller's block_until_ready)
                sp.add_stage("dispatch", dt)
        # perf-ledger attribution (consumed by the batcher on this same
        # thread): stamped AFTER the dispatch so a first-call trace of the
        # per-shard core cannot overwrite it with its inner leg's stamp.
        # Graph-mode CAGRA serves filtered traffic through its exact
        # brute-refine core, hence the filter term.
        graph_walk = self.graph_mode and filter_bits is None
        _kernels.stamp_kernel_path(
            "sharded_graph" if graph_walk else "sharded"
        )
        obs.default_registry().histogram(
            "raft_tpu_sharded_search_seconds",
            help="host-side dispatch latency of index-sharded searches "
            "(the slowest shard paces the whole SPMD step)",
        ).observe(dt, index=self.label, shards=str(self.n_shards))
        return v, i

    @property
    def n_shards(self) -> int:
        return self.comms.get_size()

    def _searcher(self, k: int, filter_bits: Optional[int] = None):
        key = (k, filter_bits)
        f = self._searchers.get(key)
        if f is None:
            f = self._build_searcher(k, filter_bits)
            self._searchers[key] = f
        return f

    def _local_pool(self) -> Tuple[int, int]:
        """(n_probes_local, candidate pool per shard) from static shapes."""
        if self.kind in ("brute_force", "cagra"):
            return 0, int(self._parts["rows"].shape[1])
        l_local = int(self._parts["list_index"].shape[1])
        cap = int(self._parts["list_index"].shape[2])
        npb = min(int(self.search_params.n_probes), l_local)
        return npb, npb * cap

    def _build_searcher(self, k: int, filter_bits: Optional[int] = None):
        mesh, axis = self.comms.mesh, self.comms.axis
        npb, pool = self._local_pool()
        kk = min(k, pool)
        if kk * self.n_shards < k:
            raise ValueError(
                f"k={k} exceeds the sharded candidate pool "
                f"{self.n_shards}x{kk}; raise n_probes or lower k"
            )
        local = self._make_local(k, kk, npb, filter_bits)
        filter_specs = () if filter_bits is None else (P(None, None),)
        in_specs = (P(None, None),) + filter_specs + tuple(
            self._specs[n] for n in self._names
        )
        return jax.jit(
            shard_map(
                local,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=(P(None, None), P(None, None)),
                check_vma=False,
            )
        )

    def _make_local(self, k: int, kk: int, npb: int,
                    filter_bits: Optional[int] = None):
        # the per-shard search and the merge selection both run under
        # nested jit, not bare in the shard_map body: older jax's
        # ShardMapTracer lacks the eager operator surface, while
        # nested-jit tracers are complete (same split as replica.py) —
        # only the all-gather collectives live in the bare body
        core = jax.jit(self._make_shard_search(kk, npb, filter_bits))
        select_min = self.select_min

        def _select(vg, ig):
            # ONE cross-shard selection; ties resolve to the smallest
            # global id regardless of shard layout (select_k_stable —
            # which routes to the fused kernels/select_k.py VMEM path at
            # merge widths, replacing the two-key full-row sort in HBM)
            return matrix.select_k_stable(
                vg.astype(jnp.float32), k,
                select_min=select_min, input_indices=ig,
            )

        sel = jax.jit(_select)

        if filter_bits is None:
            def local(q, *args):
                v, gi = core(q, *args)
                vg = self.comms.allgather(v, axis=1)
                ig = self.comms.allgather(gi, axis=1)
                return sel(vg, ig)
        else:
            def local(q, words, *args):
                v, gi = core(q, words, *args)
                vg = self.comms.allgather(v, axis=1)
                ig = self.comms.allgather(gi, axis=1)
                return sel(vg, ig)

        return local

    def _make_shard_search(self, kk: int, npb: int,
                           filter_bits: Optional[int] = None):
        """Per-shard ``(queries[, filter words], *parts) -> (dists [q,kk],
        global ids)``, squeezing the leading shard axis off every
        partitioned block and re-assembling the backend Index so the
        *existing* local search (Pallas scan legs included) runs unchanged
        over the partition.  The optional EQuARX-style bf16 cast of the
        candidate distances happens here, before the merge all-gather
        moves them.

        When ``filter_bits`` is set the core takes the replicated
        per-query global-id filter words as its second operand: IVF legs
        AND them with the tombstone bitset and pass the RowFilter through
        (list ids are global); row legs re-base the global bits onto
        local row positions before the local knn."""
        names = self._names
        merge_dtype = self.merge_dtype

        def _cast(v):
            if merge_dtype is not None and v.dtype != merge_dtype:
                return v.astype(merge_dtype)
            return v

        def _global_filter(p, words):
            """Tombstone bitset, per-query RowFilter, or their AND —
            all over the global id space the IVF list ids live in."""
            filt = _replicated_filter(p)
            if words is None:
                return filt
            if filt is not None:
                nw = min(int(filt.words.shape[0]), int(words.shape[1]))
                words = words.at[:, :nw].set(
                    words[:, :nw] & filt.words[:nw][None, :]
                )
            return RowFilter(words, filter_bits)

        if self.kind in ("brute_force", "cagra"):
            from raft_tpu.neighbors import brute_force

            def core(q, *args, words=None):
                p = dict(zip(names, args))
                rows, ids = p["rows"][0], p["ids"][0]
                if words is None:
                    filt = Bitset(p["pass_words"][0], rows.shape[0])
                else:
                    # re-base the global per-query bits onto this shard's
                    # local row positions (ids are the global row ids),
                    # folding the local pass bitset in
                    safe = jnp.clip(ids, 0, None).astype(jnp.uint32)
                    w = words[:, safe // WORD_BITS]           # [q, r]
                    bit = (w >> (safe % WORD_BITS)) & jnp.uint32(1)
                    mask = (bit == 1) & (ids >= 0)[None, :]
                    local_words = (
                        RowFilter.from_mask_rows(mask).words
                        & p["pass_words"][0][None, :]
                    )
                    filt = RowFilter(local_words, rows.shape[0])
                v, li = brute_force.knn(
                    rows, q, kk, metric=self.metric, sample_filter=filt
                )
                safe = jnp.clip(li, 0, rows.shape[0] - 1)
                gi = jnp.where(li >= 0, ids[safe], jnp.int32(-1))
                return _cast(v), gi

        elif self.kind == "ivf_flat":
            from raft_tpu.neighbors import ivf_flat

            sp = dataclasses.replace(self.search_params, n_probes=npb)

            def core(q, *args, words=None):
                p = dict(zip(names, args))
                sub = ivf_flat.Index(
                    self.metric, p["centers"][0], p["list_data"][0],
                    p["list_index"][0], p["list_sizes"][0], p["list_norms"][0],
                )
                filt = _global_filter(p, words)
                v, gi = ivf_flat.search(sp, sub, q, kk, sample_filter=filt)
                return _cast(v), gi

        else:
            from raft_tpu.neighbors import ivf_pq

            codebook_kind, pq_bits, scan_scale = self._pq_meta
            sp = dataclasses.replace(self.search_params, n_probes=npb)

            def core(q, *args, words=None):
                p = dict(zip(names, args))
                codebook = (
                    p["codebook"][0] if codebook_kind == "per_cluster"
                    else p["codebook"]
                )
                sub = ivf_pq.Index(
                    self.metric, codebook_kind, pq_bits, p["centers"][0],
                    p["centers_rot"][0], p["rotation"], codebook,
                    p["list_codes"][0], p["list_index"][0], p["list_sizes"][0],
                    p["list_data"][0], p["list_y2"][0], scan_scale=scan_scale,
                )
                filt = _global_filter(p, words)
                v, gi = ivf_pq.search(sp, sub, q, kk, sample_filter=filt)
                return _cast(v), gi

        if filter_bits is None:
            return core

        def filtered(q, words, *args):
            return core(q, *args, words=words)

        return filtered

    # -- MutableIndex-compatible serving surface ----------------------------
    def pending_mutations(self) -> Tuple[int, int]:
        """(0, 0): a sharded layout is immutable; mutate the source index
        and hot-swap a re-shard through the registry."""
        return 0, 0

    def upsert(self, vectors, ids=None):
        """Loud failure for writes forwarded after a sharded rebuild
        (a retired MutableIndex forwards mutations to its successor)."""
        raise NotImplementedError(
            "ShardedIndex is immutable: rebuild through "
            "serve.build.build_sharded (or Compactor.rebuild_sharded) and "
            "hot-swap the result"
        )

    def delete(self, ids):
        raise NotImplementedError(
            "ShardedIndex is immutable: rebuild through "
            "serve.build.build_sharded (or Compactor.rebuild_sharded) and "
            "hot-swap the result"
        )

    def device_bytes(self) -> int:
        """Total bytes across all shards (feeds the per-version live-buffer
        gauges, comparable with the unsharded index's footprint)."""
        return sum(int(a.nbytes) for a in self._parts.values())

    def per_shard_bytes(self) -> list:
        """Bytes resident on each device: sharded arrays contribute 1/N,
        replicated ones (rotation, shared codebook, filter) in full."""
        s_count = self.n_shards
        shard_b = repl_b = 0
        for name, arr in self._parts.items():
            if self._specs[name] and self._specs[name][0] is not None:
                shard_b += int(arr.nbytes) // s_count
            else:
                repl_b += int(arr.nbytes)
        return [shard_b + repl_b] * s_count

    def save(self, path: str) -> None:
        raise NotImplementedError(
            "ShardedIndex is a serving-time layout; snapshot the source "
            "index and re-shard on restore"
        )

    # -- observability -------------------------------------------------------
    def explain_contributions(self, ids) -> Dict[str, object]:
        """Per-shard counts of merged result ids — which shards the
        answer actually came from.  Deep-explain only: the ids are an
        already-copied host result, so there is no extra sync and the
        call never runs on the hot path.  Row-partitioned kinds own
        contiguous id ranges (``id // rows_per_shard``); the IVF kinds
        consult a lazily-built id→owner map from the partitioned
        ``list_index``."""
        try:
            flat = np.asarray(ids).reshape(-1)
            flat = flat[flat >= 0]
            s_count = self.n_shards
            if self.kind in ("brute_force", "cagra"):
                r = int(self._parts["rows"].shape[1])
                owner = flat // r
            else:
                owner_map = self._id_owner()
                flat = flat[flat < owner_map.shape[0]]
                owner = owner_map[flat]
            counts = np.bincount(
                owner[(owner >= 0) & (owner < s_count)], minlength=s_count
            )
            return {
                "available": True,
                "n_shards": s_count,
                "per_shard": [int(c) for c in counts[:s_count]],
            }
        except Exception as exc:  # never let explain break serving
            return {"available": False, "error": repr(exc)}

    def _id_owner(self) -> np.ndarray:
        """Cached global-id → owning-shard map for the IVF layouts
        (built once, deep-explain only)."""
        owner = getattr(self, "_owner_map", None)
        if owner is None:
            li = np.asarray(self._parts["list_index"])  # raft-tpu: ignore[HOSTSYNC] deep-explain only: one-time owner-map pull, never on the hot path
            top = int(li.max()) + 1 if li.size else 0
            owner = np.full(max(top, 0), -1, np.int32)
            for s in range(li.shape[0]):
                sid = li[s].reshape(-1)
                sid = sid[sid >= 0]
                owner[sid] = s
            self._owner_map = owner
        return owner

    def _publish_shard_gauges(self) -> None:
        """Per-shard row/list/byte gauges — the imbalance dashboard."""
        reg = obs.default_registry()
        per_bytes = self.per_shard_bytes()
        rows = self._shard_stats.get("rows")
        lists = self._shard_stats.get("lists")
        halo = self._shard_stats.get("halo")
        for s in range(self.n_shards):
            labels = {"index": self.label, "shard": str(s)}
            if rows is not None:
                reg.gauge(
                    "raft_tpu_shard_rows",
                    help="live vectors owned by each index shard",
                ).set(float(rows[s]), **labels)
            if lists is not None:
                reg.gauge(
                    "raft_tpu_shard_lists",
                    help="IVF lists owned by each index shard",
                ).set(float(lists[s]), **labels)
            if halo is not None:
                reg.gauge(
                    "raft_tpu_shard_halo_rows",
                    help="replicated halo rows held by each graph-mode "
                    "CAGRA shard (cross-cut neighbors kept so local hops "
                    "never dead-end at the partition boundary)",
                ).set(float(halo[s]), **labels)
            reg.gauge(
                "raft_tpu_shard_live_bytes",
                help="per-device bytes held by each index shard "
                "(sharded arrays at 1/N + replicated sidecars)",
            ).set(float(per_bytes[s]), **labels)

    def measure_shard_skew(self, queries, k: int) -> Dict[str, object]:
        """Per-shard device-time probe — straggler detection.

        The production :meth:`search` is ONE shard_map dispatch: the
        slowest shard paces every other, and per-shard time is invisible
        from the host.  This probe runs the *same* per-shard core search
        (Pallas legs included) over each shard's partition individually —
        warmed, then timed — and publishes
        ``raft_tpu_shard_device_seconds{index,shard}`` plus the max/mean
        straggler factor ``raft_tpu_shard_device_skew{index}``.  A skew
        near 1.0 means the round-robin partitioning is balanced; a high
        skew names the shard throttling the whole SPMD step.

        Deliberately off the hot path (operator / bench entry): compiles
        and syncs spent here never touch the batcher's zero-recompile
        contract or the serve-stage timers.
        """
        queries = jnp.asarray(queries, jnp.float32)
        if queries.ndim != 2 or queries.shape[1] != self.dim:
            raise ValueError(
                f"queries shape {queries.shape} vs index dim {self.dim}"
            )
        npb, pool = self._local_pool()
        kk = min(int(k), pool)
        core = jax.jit(self._make_shard_search(kk, npb))
        times = []
        with trace_range("serve.shard_skew"):
            for s in range(self.n_shards):
                # sharded parts contribute this shard's slice (leading
                # axis kept — the core squeezes it, exactly as the
                # shard_map body would); replicated parts ride whole
                args = tuple(
                    self._parts[n][s : s + 1]
                    if self._specs[n] and self._specs[n][0] is not None
                    else self._parts[n]
                    for n in self._names
                )
                out = core(queries, *args)
                jax.block_until_ready(out)  # raft-tpu: ignore[HOSTSYNC] probe warmup barrier
                t0 = time.perf_counter()
                out = core(queries, *args)
                jax.block_until_ready(out)  # raft-tpu: ignore[HOSTSYNC] probe timing barrier
                times.append(time.perf_counter() - t0)
        reg = obs.default_registry()
        for s, dt in enumerate(times):
            reg.gauge(
                "raft_tpu_shard_device_seconds",
                help="measured per-shard seconds for one probe search, "
                "dispatched individually outside the SPMD step",
            ).set(float(dt), index=self.label, shard=str(s))
        mean = sum(times) / len(times)
        skew = (max(times) / mean) if mean > 0.0 else 1.0
        reg.gauge(
            "raft_tpu_shard_device_skew",
            help="max/mean of the per-shard probe times — the straggler "
            "factor pacing the real sharded dispatch",
        ).set(float(skew), index=self.label)
        return {"per_shard_s": times, "skew": skew}


def _infer_kind(index) -> str:
    mod = type(index).__module__.rsplit(".", 1)[-1]
    if mod not in ("brute_force", "ivf_flat", "ivf_pq", "cagra"):
        raise ValueError(
            f"cannot infer index kind from {type(index)!r}; pass a built "
            "brute_force/ivf_flat/ivf_pq/cagra index or a MutableIndex"
        )
    return mod


def _partition_lists(arrays, fills, s_count):
    """Round-robin the leading (list) axis of every array into [S, Lp, ...]
    stacks, padding with empty lists (sizes 0, ids −1, norms inf)."""
    l_total = arrays["list_index"].shape[0]
    groups = _round_robin(l_total, s_count)
    lp = max(len(g) for g in groups)
    out = {}
    for name, arr in arrays.items():
        fill = fills.get(name, 0)
        stack = np.full((s_count, lp) + arr.shape[1:], fill, arr.dtype)
        for s, g in enumerate(groups):
            if len(g):
                stack[s, : len(g)] = arr[g]
                if name == "centers" and len(g) < lp:
                    # padded slots re-use a real center: they may attract
                    # probes (wasting one) but their lists are empty, so
                    # every candidate they yield is (−1, worst) — harmless
                    stack[s, len(g):] = arr[g[0]]
        out[name] = stack
    sizes = arrays["list_sizes"]
    stats = {
        "lists": [len(g) for g in groups],
        "rows": [int(sizes[g].sum()) for g in groups],
    }
    return out, stats


def _global_pass_filter(deleted, n_main):
    """Replicated global-id pass bitset words (IVF ids are global)."""
    if deleted is None:
        return {}
    return {"pass_words": _pack_pass_words(~deleted[:n_main])}


def _replicated_filter(parts):
    words = parts.get("pass_words")
    if words is None:
        return None
    return Bitset(words, int(words.shape[0]) * WORD_BITS)


def _place(comms, *, sharded, replicated):
    """device_put every array with its NamedSharding: sharded stacks split
    on the leading (shard) axis, sidecars replicated on every device."""
    mesh, axis = comms.mesh, comms.axis
    parts, specs = {}, {}
    for name, arr in sharded.items():
        spec = P(axis, *([None] * (arr.ndim - 1)))
        parts[name] = jax.device_put(arr, NamedSharding(mesh, spec))
        specs[name] = spec
    for name, arr in replicated.items():
        spec = P(*([None] * arr.ndim))
        parts[name] = jax.device_put(arr, NamedSharding(mesh, spec))
        specs[name] = spec
    return parts, specs


def shard_index(index, comms: Optional[Comms] = None, **kwargs) -> ShardedIndex:
    """Convenience alias for :meth:`ShardedIndex.from_index`."""
    return ShardedIndex.from_index(index, comms, **kwargs)
