"""Pod-scale distributed index build: train the index where the data lives.

``ShardedIndex.from_index`` scales *serving* — but it still requires a
single-host build first, which caps the buildable index at one host's
memory and one chip's FLOPs.  This module closes that gap:
:func:`build_sharded` trains brute_force / ivf_flat / ivf_pq / cagra
indexes over training data that stays row-sharded across a named mesh
axis, and returns a :class:`~raft_tpu.serve.shard.ShardedIndex` already
in its round-robin serving layout — hot-swappable through
``IndexRegistry`` with zero extra re-shard step.

What runs sharded (the O(n·d·k) legs — all per-iteration compute and
collectives are mesh-resident):

- **Coarse k-means** (ivf_flat/ivf_pq): every Lloyd iteration computes
  local assignments and partial centroid sums/counts on each shard's
  rows, then merges them with ONE packed ``psum`` per iteration
  (:func:`raft_tpu.cluster.kmeans_balanced.fit_sharded`).  The psum
  payload can be quantized EQuARX-style
  (``RAFT_TPU_BUILD_REDUCE_DTYPE=bfloat16|int8`` — see
  :mod:`raft_tpu.comms.quantized`): centroid partial sums tolerate low
  precision because each shard's contribution is renormalized by the
  global counts.
- **PQ codebook fitting** (ivf_pq per_subspace): per-subspace k-means
  over the *sharded* rotated residuals — one packed [pq_dim, k_pq,
  pq_len+1] sums|counts psum per Lloyd iteration, same quantization
  knob.
- **CAGRA kNN graph**: a ring of ``ppermute`` block exchanges.  Each of
  the S steps moves one shard-block of rows one hop around the ring;
  every shard scores its own rows against the visiting block
  (optionally in ``RAFT_TPU_BUILD_KNN_BLOCK_ROWS``-row column tiles to
  bound the distance matrix) and folds the block's top-k into a running
  tie-stable merge (:func:`~raft_tpu.ops.matrix.select_k_stable`), so
  the resulting graph is partition-invariant: identical to the
  single-host exact kNN regardless of how rows were sharded.  Rows
  travel around the ring exactly once; no all-gather of the dataset.

What is host-mediated (one-time layout staging, NOT per-iteration): the
final list assembly moves each row (ivf_flat) or its compressed PQ code
(ivf_pq — ``pq_dim`` bytes/row) to its destination list.  This is the
same host-staged transposition the existing
``comms.distributed.sharded_ivf_pq_build`` and
``ShardedIndex.from_index`` use, standing in for a DCN all-to-all; the
expensive training legs never funnel through it.

Layout: the sharded assembly targets ``ShardedIndex``'s round-robin
list placement *directly* via a shard-major relabel — global list ``l``
lives on shard ``l % S`` at slot ``l // S``, so relabeling
``l' = (l % S)·Lp + (l // S)`` (``Lp = ceil(L/S)``) and packing
``S·Lp`` lists in one pass (list splitting disabled — ``max_cap=None``)
yields, after a ``[S·Lp, ...] → [S, Lp, ...]`` reshape, exactly the
stacks ``_partition_lists`` would have produced from a single-host
index.  Padded slots reuse a real center and carry empty lists, same as
the re-shard path.

Observability: each phase sets the ``raft_tpu_build_phase`` /
``raft_tpu_build_rows_done`` gauges and opens a ``serve.build.<phase>``
span; completion publishes a ``build_complete`` event on the bus.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu import obs
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.comms.comms import Comms, local_comms
from raft_tpu.comms.quantized import quantized_psum, reduce_dtype_from_env
from raft_tpu.core import env as _env
from raft_tpu.core.compat import shard_map
from raft_tpu.core.logger import logger as _log
from raft_tpu.core.resources import Resources, ensure
from raft_tpu.core.trace import trace_range, traced
from raft_tpu.distance.pairwise import DISTANCE_TYPES, _PREC, distance_matrix_tile
from raft_tpu.obs import events
from raft_tpu.ops import matrix
from raft_tpu.serve.shard import (
    ShardedIndex,
    _pack_pass_words,
    _place,
    _resolve_cagra_mode,
    merge_dtype_from_env,
)

#: env knob: column-tile rows of the ring kNN exchange (bounds the
#: [my_rows, tile] distance matrix; default = one shard's rows per step)
KNN_BLOCK_ENV = "RAFT_TPU_BUILD_KNN_BLOCK_ROWS"

#: build phases in execution order — the ``raft_tpu_build_phase`` gauge
#: reports the current phase as an index into this tuple
PHASES = (
    "place",      # pad + device_put the training rows across the mesh
    "coarse",     # sharded balanced k-means (ivf_flat / ivf_pq)
    "codebooks",  # sharded per-subspace PQ codebook fit (ivf_pq)
    "encode",     # sharded residual PQ encode (ivf_pq)
    "knn_graph",  # ring-of-ppermute exact kNN graph (cagra)
    "assemble",   # shard-major list assembly into the serving layout
    "finalize",   # graph prune / index construction / placement
)

_BUILD_KINDS = ("brute_force", "ivf_flat", "ivf_pq", "cagra")


@contextlib.contextmanager
def _phase(label: str, name: str):
    """One build phase: gauge + span (``serve.build.<name>``)."""
    obs.default_registry().gauge(
        "raft_tpu_build_phase",
        help="current distributed-build phase (index into serve.build.PHASES)",
    ).set(float(PHASES.index(name)), index=label)
    with trace_range(f"serve.build.{name}"):
        yield


def _rows_done(label: str, n: int) -> None:
    obs.default_registry().gauge(
        "raft_tpu_build_rows_done",
        help="rows the distributed build has processed through its "
        "current phase",
    ).set(float(n), index=label)


def knn_block_rows_from_env(r: int) -> int:
    """Ring-exchange column tile: env override clamped to [8, r]."""
    b = _env.env_int(KNN_BLOCK_ENV)
    if b is None:
        return r
    return int(max(8, min(int(b), r)))


# -- data placement ----------------------------------------------------------

def _place_rows(comms: Comms, data) -> Tuple[np.ndarray, jax.Array, jax.Array, int]:
    """Pad ``data`` to a shard-divisible row count and place it.

    Returns ``(data_np [n_pad, d] host, x_sharded [n_pad, d] P(axis, None),
    weights [n_pad] P(axis) — 1.0 real / 0.0 padding, n_real)``.  Padding
    rows sit at the END of the global id space so every builder can mask
    them with ``gid < n``.
    """
    mesh, axis = comms.mesh, comms.axis
    s_count = comms.get_size()
    data_np = np.asarray(data)
    if data_np.ndim != 2:
        raise ValueError(f"expected [n, dim] training data, got {data_np.shape}")
    n, d = data_np.shape
    if n < s_count:
        raise ValueError(f"need at least one row per shard: n={n} < {s_count}")
    r = -(-n // s_count)
    n_pad = r * s_count
    if n_pad != n:
        data_np = np.concatenate(
            [data_np, np.zeros((n_pad - n, d), data_np.dtype)]
        )
    w = np.zeros((n_pad,), np.float32)
    w[:n] = 1.0
    x_sh = jax.device_put(data_np, NamedSharding(mesh, P(axis, None)))
    w_sh = jax.device_put(w, NamedSharding(mesh, P(axis)))
    return data_np, x_sh, w_sh, n


def _shard_major_relabel(labels: np.ndarray, n_lists: int, s_count: int):
    """Relabel global list ids into the round-robin serving layout.

    Global list ``l`` serves from shard ``l % S``, local slot ``l // S``
    (``_round_robin``); packing labels ``l' = (l % S)·Lp + l // S`` over
    ``S·Lp`` lists makes the flat [S·Lp, ...] assembly reshape directly
    into the per-shard stacks.  Returns ``(relabeled, lp, src)`` where
    ``src[l']`` is the global list backing padded slot ``l'`` (padded
    slots reuse the shard's first real list's center, matching
    ``_partition_lists``).
    """
    lp = -(-n_lists // s_count)
    labels = np.asarray(labels)
    relab = (labels % s_count) * lp + labels // s_count
    flat = np.arange(s_count * lp)
    s_idx, j_idx = flat // lp, flat % lp
    g = s_idx + j_idx * s_count
    src = np.where(g < n_lists, g, s_idx)
    return relab.astype(np.int64), lp, src


def _list_stats(n_lists: int, s_count: int, sizes: np.ndarray):
    """Per-shard (real) list and row counts for ``shard_stats``."""
    lists = [len(range(s, n_lists, s_count)) for s in range(s_count)]
    per_shard = sizes.reshape(s_count, -1)
    return {"lists": lists, "rows": [int(r.sum()) for r in per_shard]}


# -- sharded ring kNN (cagra) ------------------------------------------------

@functools.lru_cache(maxsize=32)
def _ring_knn_program(mesh, axis, s_count: int, n_real: int, k_sel: int,
                      metric: str, block_rows: int):
    """Exact kNN ids over row-sharded data via a ring of ppermute steps.

    Each shard keeps its own rows resident and scores them against the
    visiting block, folding per-tile top-k into a running tie-stable
    merge.  Candidate ids are globalized per visiting block (``owner·r +
    col``), so the merged graph is identical to the single-host exact
    kNN — ties resolve to the smallest global id on every partition
    (partition invariance; tested in test_build_sharded.py).
    """
    select_min = DISTANCE_TYPES[metric] != "inner_product"
    worst = jnp.inf if select_min else -jnp.inf

    def local(x):
        rank = lax.axis_index(axis)
        my = x.astype(jnp.float32)
        r = my.shape[0]
        n_tiles = -(-r // block_rows)
        r_pad = n_tiles * block_rows
        kk = min(k_sel, block_rows)

        vals0 = jnp.full((r, k_sel), worst, jnp.float32)
        gids0 = jnp.full((r, k_sel), -1, jnp.int32)
        blk0 = jnp.pad(my, ((0, r_pad - r), (0, 0)))

        def tile_fold(carry, t, blk, owner):
            vals, gids = carry
            cols = lax.dynamic_slice_in_dim(blk, t * block_rows, block_rows, 0)
            d2 = distance_matrix_tile(my, cols, metric)       # [r, block]
            # mask tile padding (col >= r) and global padding (gi >= n)
            # BEFORE the per-tile select: a zero-padded fake row scores a
            # finite distance and would displace real candidates from the
            # tile's top-k otherwise
            col_all = t * block_rows + jnp.arange(block_rows, dtype=jnp.int32)
            ok_all = (col_all < r) & (owner * r + col_all < n_real)
            d2 = jnp.where(ok_all[None, :], d2, worst)
            v, li = matrix.select_k(d2, kk, select_min=select_min)
            col = t * block_rows + li
            gi = owner * r + col
            ok = (col < r) & (gi < n_real)
            v = jnp.where(ok, v, worst)
            gi = jnp.where(ok, gi, -1)
            return matrix.select_k_stable(
                jnp.concatenate([vals, v], axis=1), k_sel,
                select_min=select_min,
                input_indices=jnp.concatenate([gids, gi], axis=1),
            ), None

        def hop(carry, t):
            vals, gids, blk = carry
            owner = (rank - t) % s_count
            (vals, gids), _ = lax.scan(
                functools.partial(tile_fold, blk=blk, owner=owner),
                (vals, gids), jnp.arange(n_tiles),
            )
            # send my current block one hop around the ring (i -> i+1);
            # after step t every shard holds block (rank - t - 1) % S
            blk = lax.ppermute(
                blk, axis, [(i, (i + 1) % s_count) for i in range(s_count)]
            )
            return (vals, gids, blk), None

        (vals, gids, _), _ = lax.scan(
            hop, (vals0, gids0, blk0), jnp.arange(s_count)
        )
        # drop self (always distance 0 in L2 / max-sim in IP) the same way
        # nn_descent.build_exact does: stable-sort the self column to the
        # end, keep the first k_sel - 1
        myid = rank * r + jnp.arange(r, dtype=jnp.int32)
        self_col = gids == myid[:, None]
        order = jnp.argsort(self_col, axis=1, stable=True)
        gids = jnp.take_along_axis(gids, order, axis=1)[:, : k_sel - 1]
        return gids

    return jax.jit(
        shard_map(
            local, mesh=mesh,
            in_specs=(P(axis, None),),
            out_specs=P(axis, None),
            check_vma=False,
        )
    )


@traced("serve.build.knn_graph")
def knn_graph_sharded(comms: Comms, data, k: int, *, metric: str = "sqeuclidean",
                      block_rows: Optional[int] = None) -> np.ndarray:
    """Exact [n, k] neighbor-id graph (self excluded, rows sorted by
    distance) built with the ring exchange — each row crosses the
    interconnect exactly once."""
    data_np, x_sh, _, n = _place_rows(comms, data)
    r = data_np.shape[0] // comms.get_size()
    if k + 1 > n:
        raise ValueError(f"k={k} needs at least k+1 rows, got n={n}")
    b = block_rows if block_rows is not None else knn_block_rows_from_env(r)
    run = _ring_knn_program(
        comms.mesh, comms.axis, comms.get_size(), n, k + 1, metric, int(b)
    )
    return np.asarray(run(x_sh))[:n]


# -- sharded PQ codebook fit (ivf_pq) ----------------------------------------

@functools.lru_cache(maxsize=32)
def _pq_codebooks_program(mesh, axis, n_iters: int, reduce_dtype: str):
    """Per-subspace Lloyd over sharded rotated residuals: ONE packed
    [pq_dim, k_pq, pq_len+1] sums|counts psum per iteration (optionally
    quantized).  The [r, pq_dim, k_pq] one-hot assignment is bounded by
    the per-shard row count — the point of training sharded."""

    def local(x, labels, w, centers, rotation, cb0):
        x32 = x.astype(jnp.float32)
        resid = jnp.matmul(
            x32 - centers[labels], rotation.T, precision=_PREC
        )
        pq_dim, k_pq, pq_len = cb0.shape
        sub = resid.reshape(resid.shape[0], pq_dim, pq_len)

        def body(cb, _):
            ip = jnp.einsum("njl,jkl->njk", sub, cb, precision=_PREC)
            cb2 = jnp.sum(cb * cb, axis=2)
            codes = jnp.argmin(cb2[None] - 2.0 * ip, axis=2)   # [r, pq_dim]
            hot = jax.nn.one_hot(codes, k_pq, dtype=jnp.float32)
            hot = hot * w[:, None, None]
            sums = jnp.einsum("njk,njl->jkl", hot, sub, precision=_PREC)
            counts = jnp.sum(hot, axis=0)                      # [pq_dim, k_pq]
            packed = jnp.concatenate([sums, counts[..., None]], axis=-1)
            packed = quantized_psum(packed, axis, reduce_dtype)
            g_sums = packed[..., :pq_len]
            g_counts = packed[..., pq_len]
            cb = jnp.where(
                g_counts[..., None] > 0.0,
                g_sums / jnp.maximum(g_counts, 1.0)[..., None],
                cb,
            )
            return cb, None

        cb, _ = lax.scan(body, cb0.astype(jnp.float32), None, length=n_iters)
        return cb

    return jax.jit(
        shard_map(
            local, mesh=mesh,
            in_specs=(
                P(axis, None), P(axis), P(axis),
                P(None, None), P(None, None), P(None, None, None),
            ),
            out_specs=P(None, None, None),
            check_vma=False,
        )
    )


@functools.lru_cache(maxsize=32)
def _encode_program(mesh, axis, codebook_kind: str):
    """Shard-local residual PQ encode — rows never leave their shard;
    only the pq_dim-byte codes are staged out for assembly."""
    from raft_tpu.neighbors import ivf_pq

    def local(x, labels, centers, centers_rot, rotation, codebook):
        return ivf_pq._encode(
            rotation, centers, centers_rot, codebook,
            x.astype(jnp.float32), labels, codebook_kind,
        )

    return jax.jit(
        shard_map(
            local, mesh=mesh,
            in_specs=(
                P(axis, None), P(axis), P(None, None), P(None, None),
                P(None, None), P(None, None, None),
            ),
            out_specs=P(axis, None),
            check_vma=False,
        )
    )


def _seed_subsample(key, data_np: np.ndarray, n: int, n_sub: int):
    """Replicated seeding rows: a with-replacement draw from the REAL
    rows (padding excluded by construction — ids < n)."""
    idx = np.asarray(
        jax.random.randint(key, (min(n, n_sub),), 0, n)
    )
    return jnp.asarray(data_np[idx], jnp.float32)


# -- per-kind builders -------------------------------------------------------

def _build_rows_sharded(comms, kind, data_np, x_sh, n, metric, merge_dtype,
                        label, params, res, search_params=None,
                        cagra_mode="env"):
    """brute_force / cagra: the serving layout IS the training layout —
    contiguous row blocks with global arange ids.  cagra additionally
    builds its pruned search graph from the ring kNN graph; with
    ``cagra_mode="graph"`` the build emits the partitioned-graph serving
    layout (:class:`~raft_tpu.serve.graph_shard.GraphShardedIndex`)
    directly from that graph instead of the brute-refine row blocks."""
    s_count = comms.get_size()
    n_pad, d = data_np.shape
    r = n_pad // s_count

    graph = None
    if kind == "cagra":
        from raft_tpu.neighbors import cagra

        params = params if params is not None else cagra.IndexParams()
        metric = params.metric
        inter = min(int(params.intermediate_graph_degree), n - 1)
        with _phase(label, "knn_graph"):
            knn = knn_graph_sharded(comms, data_np[:n], inter, metric=metric)
            _rows_done(label, n)
        with _phase(label, "finalize"):
            degree = min(int(params.graph_degree), inter)
            graph = np.asarray(
                cagra.optimize(jnp.asarray(knn, jnp.int32), degree, res=res)
            )
        if _resolve_cagra_mode(cagra_mode) == "graph":
            from raft_tpu.serve.graph_shard import GraphShardedIndex

            with _phase(label, "assemble"):
                # partitioned-graph serving layout straight from the ring
                # kNN graph: entry-point table + a transient single-host
                # Index shell, cluster-cut and halo'd by _shard_graph
                dataset = jnp.asarray(data_np[:n])
                canonical = DISTANCE_TYPES[metric]
                n_entries = params.entry_points
                if n_entries is None:
                    n_entries = cagra._auto_entry_points(n)
                n_entries = min(n_entries, n)
                entry_centers = entry_ids = None
                if n_entries:
                    entry_centers, entry_ids = cagra._build_entry_points(
                        dataset, n_entries, canonical, params.seed, res
                    )
                tmp = cagra.Index(
                    metric, dataset, jnp.asarray(graph, jnp.int32),
                    entry_centers, entry_ids,
                )
                index = GraphShardedIndex._shard_graph(
                    comms, tmp, None, search_params, merge_dtype, label
                )
                _rows_done(label, n)
            # the pruned graph stays a build artifact for single-device
            # consumers (cagra.from_graph), same as the brute layout below
            index.cagra_graph = graph
            return index

    with _phase(label, "assemble"):
        ids = np.full((s_count, r), -1, np.int32)
        words = np.zeros(
            (s_count, _pack_pass_words(np.ones(r, bool)).shape[0]), np.uint32
        )
        row_counts = []
        for s in range(s_count):
            lo, hi = s * r, min((s + 1) * r, n)
            m = max(hi - lo, 0)
            if m > 0:
                ids[s, :m] = np.arange(lo, hi, dtype=np.int32)
            passes = np.zeros((r,), bool)
            passes[:m] = True
            words[s] = _pack_pass_words(passes)
            row_counts.append(m)
        mesh, axis = comms.mesh, comms.axis
        rows = jax.device_put(
            data_np.reshape(s_count, r, d),
            NamedSharding(mesh, P(axis, None, None)),
        )
        parts, specs = _place(
            comms, sharded={"ids": ids, "pass_words": words}, replicated={}
        )
        parts["rows"] = rows
        specs["rows"] = P(axis, None, None)
        _rows_done(label, n)

    index = ShardedIndex(
        comms, kind, metric, d, n, parts, specs,
        merge_dtype=merge_dtype, label=label,
        shard_stats={"rows": row_counts},
    )
    if graph is not None:
        # the pruned CAGRA search graph: sharded serving runs the
        # row-partitioned brute fallback (same as from_index), but the
        # graph is the build artifact single-device consumers feed to
        # cagra.from_graph
        index.cagra_graph = graph
    return index


def _build_ivf_flat_sharded(comms, data_np, x_sh, w_sh, n, params,
                            search_params, merge_dtype, reduce_dtype, label,
                            res):
    from raft_tpu.neighbors import ivf_flat

    params = params if params is not None else ivf_flat.IndexParams()
    canonical = DISTANCE_TYPES[params.metric]
    if canonical not in ("sqeuclidean", "euclidean", "inner_product", "cosine"):
        raise ValueError(
            f"ivf_flat supports L2/IP/cosine metrics, got {params.metric}"
        )
    s_count = comms.get_size()
    d = data_np.shape[1]

    with _phase(label, "coarse"):
        kb_metric = (
            canonical if canonical in ("cosine", "inner_product")
            else "sqeuclidean"
        )
        kb = kmeans_balanced.KMeansBalancedParams(
            n_iters=params.kmeans_n_iters, metric=kb_metric, seed=params.seed
        )
        centers, labels_sh = kmeans_balanced.fit_sharded(
            comms, kb, x_sh, params.n_lists, sample_weights=w_sh,
            reduce_dtype=reduce_dtype, res=res,
        )
        labels = np.asarray(labels_sh)[:n]
        _rows_done(label, n)

    with _phase(label, "assemble"):
        relab, lp, src = _shard_major_relabel(labels, params.n_lists, s_count)
        l_data, l_index, sizes, l_norms, center_map = ivf_flat._pack_lists(
            data_np[:n], np.arange(n, dtype=np.int32), relab,
            s_count * lp, params.metric,
            headroom=not params.conservative_memory_allocation,
            max_cap=None,
        )
        centers_np = np.asarray(centers)[src]           # [S*Lp, d]
        cap = l_data.shape[1]
        sharded = {
            "centers": centers_np.reshape(s_count, lp, d),
            "list_data": l_data.reshape(s_count, lp, cap, d),
            "list_index": l_index.reshape(s_count, lp, cap),
            "list_sizes": sizes.reshape(s_count, lp),
            "list_norms": l_norms.reshape(s_count, lp, cap),
        }
        stats = _list_stats(params.n_lists, s_count, np.asarray(sizes))
        _rows_done(label, n)

    with _phase(label, "finalize"):
        parts, specs = _place(comms, sharded=sharded, replicated={})
    return ShardedIndex(
        comms, "ivf_flat", params.metric, d, n, parts, specs,
        search_params=(
            search_params if search_params is not None
            else ivf_flat.SearchParams()
        ),
        merge_dtype=merge_dtype, label=label, shard_stats=stats,
    )


def _build_ivf_pq_sharded(comms, data_np, x_sh, w_sh, n, params,
                          search_params, merge_dtype, reduce_dtype, label,
                          res):
    from raft_tpu.neighbors import ivf_pq

    params = params if params is not None else ivf_pq.IndexParams()
    canonical = DISTANCE_TYPES[params.metric]
    if canonical not in ("sqeuclidean", "euclidean", "inner_product"):
        raise ValueError(f"ivf_pq supports L2/IP metrics, got {params.metric}")
    if not (4 <= params.pq_bits <= 8):
        raise ValueError(f"pq_bits must be in [4, 8], got {params.pq_bits}")
    s_count = comms.get_size()
    d = data_np.shape[1]
    pq_dim = params.pq_dim or ivf_pq._auto_pq_dim(d)
    pq_len = max(1, (d + pq_dim - 1) // pq_dim)
    rot_dim = pq_dim * pq_len
    k_pq = 1 << params.pq_bits
    key = jax.random.PRNGKey(params.seed)
    _, k_rot, k_cb = jax.random.split(key, 3)

    with _phase(label, "coarse"):
        kb_metric = (
            "inner_product" if canonical == "inner_product" else "sqeuclidean"
        )
        kb = kmeans_balanced.KMeansBalancedParams(
            n_iters=params.kmeans_n_iters, metric=kb_metric, seed=params.seed
        )
        centers, labels_sh = kmeans_balanced.fit_sharded(
            comms, kb, x_sh, params.n_lists, sample_weights=w_sh,
            reduce_dtype=reduce_dtype, res=res,
        )
        rotation = ivf_pq.make_rotation_matrix(
            k_rot, rot_dim, d, params.force_random_rotation
        )
        centers_rot = jnp.matmul(centers, rotation.T, precision=_PREC)
        _rows_done(label, n)

    with _phase(label, "codebooks"):
        # replicated seeding subsample (rows travel once, ~8·k_pq of them),
        # then the full sharded refine — every iteration one packed psum
        n_sub = min(n, max(8 * k_pq, 4096))
        x_sub = _seed_subsample(jax.random.fold_in(k_cb, 1), data_np, n, n_sub)
        lab_sub = kmeans_balanced.predict(
            centers, x_sub, metric=kb_metric, res=res
        )
        resid_sub = jnp.matmul(
            x_sub - centers[lab_sub], rotation.T, precision=_PREC
        )
        if params.codebook_kind == ivf_pq.CODEBOOK_PER_SUBSPACE:
            sub_t = jnp.transpose(
                resid_sub.reshape(-1, pq_dim, pq_len), (1, 0, 2)
            )
            cb0 = ivf_pq._train_codebooks_lloyd(k_cb, sub_t, k_pq, 2)
            refine = _pq_codebooks_program(
                comms.mesh, comms.axis, 25, reduce_dtype
            )
            codebook = refine(x_sh, labels_sh, w_sh, centers, rotation, cb0)
        elif params.codebook_kind == ivf_pq.CODEBOOK_PER_CLUSTER:
            # per_cluster wants one k-means per LIST — n_lists independent
            # small problems that gain nothing from a cross-shard reduce;
            # train them on the replicated residual subsample (the
            # single-host build subsamples here too)
            codebook = _per_cluster_codebooks(
                k_cb, resid_sub, np.asarray(lab_sub), params.n_lists,
                k_pq, pq_len, pq_dim,
            )
        else:
            raise ValueError(f"unknown codebook_kind {params.codebook_kind}")

    with _phase(label, "encode"):
        enc = _encode_program(comms.mesh, comms.axis, params.codebook_kind)
        codes_sh = enc(x_sh, labels_sh, centers, centers_rot, rotation, codebook)
        # compressed stream off the mesh: pq_dim bytes/row + the labels —
        # the DCN all-to-all stand-in (rows themselves never move)
        codes = np.asarray(codes_sh)[:n]
        labels = np.asarray(labels_sh)[:n]
        _rows_done(label, n)

    with _phase(label, "assemble"):
        relab, lp, src = _shard_major_relabel(labels, params.n_lists, s_count)
        centers_rot_np = np.asarray(centers_rot)[src]
        cb_assemble = codebook
        if params.codebook_kind == ivf_pq.CODEBOOK_PER_CLUSTER:
            cb_assemble = jnp.asarray(np.asarray(codebook)[src])
        dec_dtype = _resolve_decoded_dtype(params, n, rot_dim, pq_dim)
        l_codes, l_index, sizes, l_data, l_y2, _, scale = ivf_pq._assemble_lists(
            codes, np.arange(n, dtype=np.int32), relab, s_count * lp,
            cb_assemble, params.codebook_kind, centers_rot_np, dec_dtype,
            headroom=not params.conservative_memory_allocation,
            max_cap=None,
        )
        cap = l_codes.shape[1]
        sharded = {
            "centers": np.asarray(centers)[src].reshape(s_count, lp, d),
            "centers_rot": centers_rot_np.reshape(s_count, lp, rot_dim),
            "list_codes": l_codes.reshape(s_count, lp, cap, pq_dim),
            "list_index": l_index.reshape(s_count, lp, cap),
            "list_sizes": sizes.reshape(s_count, lp),
            "list_data": l_data.reshape(s_count, lp, cap, rot_dim),
            "list_y2": l_y2.reshape(s_count, lp, cap),
        }
        replicated = {"rotation": np.asarray(rotation)}
        if params.codebook_kind == ivf_pq.CODEBOOK_PER_CLUSTER:
            sharded["codebook"] = np.asarray(cb_assemble).reshape(
                s_count, lp, k_pq, pq_len
            )
        else:
            replicated["codebook"] = np.asarray(codebook)
        stats = _list_stats(params.n_lists, s_count, np.asarray(sizes))
        _rows_done(label, n)

    with _phase(label, "finalize"):
        parts, specs = _place(comms, sharded=sharded, replicated=replicated)
    index = ShardedIndex(
        comms, "ivf_pq", params.metric, d, n, parts, specs,
        search_params=(
            search_params if search_params is not None
            else ivf_pq.SearchParams()
        ),
        merge_dtype=merge_dtype, label=label, shard_stats=stats,
    )
    index._pq_meta = (params.codebook_kind, int(params.pq_bits), float(scale))
    return index


def _per_cluster_codebooks(key, resid, labels, n_lists, k_pq, pq_len, pq_dim):
    """Pooled per-cluster codebook training on a replicated residual
    subsample (mirrors ivf_pq.build's counting-sort pooling)."""
    from raft_tpu.neighbors import ivf_pq

    flat = np.asarray(resid).reshape(-1, pq_len)
    lab2 = np.repeat(labels, pq_dim)
    counts = np.bincount(lab2, minlength=n_lists)
    cap = max(int(counts.max()) if counts.size else 1, k_pq)
    cap = min(cap, max(8 * k_pq, 2048))
    order = np.argsort(lab2, kind="stable")
    starts = np.cumsum(counts) - counts
    within = np.arange(len(lab2)) - starts[lab2[order]]
    keep = within < cap
    pooled = np.zeros((n_lists, cap, pq_len), np.float32)
    wts = np.zeros((n_lists, cap), np.float32)
    pooled[lab2[order][keep], within[keep]] = flat[order][keep]
    wts[lab2[order][keep], within[keep]] = 1.0
    return ivf_pq._train_codebooks_lloyd(
        key, jnp.asarray(pooled), k_pq, 25, jnp.asarray(wts)
    )


def _resolve_decoded_dtype(params, n, rot_dim, pq_dim):
    """The single-host build's decoded-dtype ladder, shared verbatim:
    bf16 unless the projected cache exceeds a REAL device limit."""
    from raft_tpu.neighbors import ivf_pq

    decoded = params.decoded_dtype
    if decoded == "auto":
        est_rows = int(n * 1.35) + 8 * params.n_lists
        bf16_bytes = est_rows * (rot_dim * 2 + pq_dim + 8)
        total, limit_is_real = ivf_pq._device_memory_budget()
        budget = int(ivf_pq._AUTO_HBM_FRACTION * total)
        decoded = "int8" if bf16_bytes > budget and limit_is_real else "bfloat16"
    if decoded not in ivf_pq._DECODED_DTYPES:
        raise ValueError(f"unknown decoded_dtype {decoded!r}")
    return ivf_pq._DECODED_DTYPES[decoded]


# -- entry point -------------------------------------------------------------

@traced("serve.build")
def build_sharded(
    kind: str,
    data,
    comms: Optional[Comms] = None,
    *,
    n_devices: Optional[int] = None,
    index_params=None,
    search_params=None,
    metric: str = "sqeuclidean",
    merge_dtype="env",
    reduce_dtype: Optional[str] = None,
    label: str = "",
    cagra_mode: str = "env",
    res: Optional[Resources] = None,
) -> ShardedIndex:
    """Build a :class:`ShardedIndex` of ``kind`` with the training data
    row-sharded across ``comms``'s mesh axis.

    ``data`` may be a host array (placed here, padded to a
    shard-divisible row count with zero-weight rows) or an already
    mesh-sharded ``[n, dim]`` array.  ``index_params`` is the backend's
    ``IndexParams`` (``metric`` is only read for brute_force, which has
    none).  ``reduce_dtype`` quantizes the per-iteration training
    collectives (default: ``RAFT_TPU_BUILD_REDUCE_DTYPE``);
    ``merge_dtype`` is the *serving* merge knob, same as
    ``ShardedIndex.from_index``.

    The result is already in serving layout — register it and hot-swap
    through ``IndexRegistry`` like any re-sharded index; ``Compactor``
    uses it as its distributed rebuild leg
    (:meth:`raft_tpu.serve.compactor.Compactor.rebuild_sharded`).

    ``cagra_mode`` picks the CAGRA serving layout the build emits:
    ``"brute"`` (row-partitioned brute refine — exact), ``"graph"``
    (partitioned graph traversal with halo frontiers, built directly
    from the ring kNN graph), or ``"env"`` (``RAFT_TPU_SHARD_CAGRA``).
    """
    if kind not in _BUILD_KINDS:
        raise ValueError(
            f"unsupported index kind {kind!r}; expected one of {_BUILD_KINDS}"
        )
    comms = comms if comms is not None else local_comms(n_devices)
    if merge_dtype == "env":
        merge_dtype = merge_dtype_from_env()
    if reduce_dtype is None:
        reduce_dtype = reduce_dtype_from_env()
    res = ensure(res)
    lbl = label or f"{kind}-sharded"
    t0 = time.perf_counter()

    with _phase(lbl, "place"):
        data_np, x_sh, w_sh, n = _place_rows(comms, data)

    if kind in ("brute_force", "cagra"):
        index = _build_rows_sharded(
            comms, kind, data_np, x_sh, n, metric, merge_dtype, lbl,
            index_params, res, search_params=search_params,
            cagra_mode=cagra_mode,
        )
    elif kind == "ivf_flat":
        index = _build_ivf_flat_sharded(
            comms, data_np, x_sh, w_sh, n, index_params, search_params,
            merge_dtype, reduce_dtype, lbl, res,
        )
    else:
        index = _build_ivf_pq_sharded(
            comms, data_np, x_sh, w_sh, n, index_params, search_params,
            merge_dtype, reduce_dtype, lbl, res,
        )

    wall = time.perf_counter() - t0
    events.publish(
        "build_complete",
        reason=f"distributed {kind} build",
        index=lbl, index_kind=kind, rows=n, shards=comms.get_size(),
        seconds=round(wall, 4), reduce_dtype=reduce_dtype,
    )
    _log.info(
        "build_sharded: kind=%s n=%d dim=%d shards=%d reduce=%s %.3fs",
        kind, n, data_np.shape[1], comms.get_size(), reduce_dtype, wall,
    )
    return index
