"""Multi-chip serving: replicated index, query-sharded dispatch.

Query serving scales differently from index building: the index fits on
one chip (or is already sharded by comms/), and the scarce resource is
*query throughput*.  The serving answer is data parallelism over the
query stream — the index is replicated across the mesh axis, a batch of
queries shards ``P(axis, None)``, every device runs the full search on
its slice, and the per-shard results all-gather back replicated (the
same shape the single-device search returns, so the batcher cannot tell
the difference).  N devices ≈ N× the batch throughput at identical
per-query results.

This composes with the rest of the serve stack: ``ReplicaGroup`` wraps an
:class:`~raft_tpu.serve.registry.IndexRegistry`, so hot-swap and
mutations behave exactly as in the single-chip path (the snapshot a
search closes over is replicated at trace time).  It also composes with
pipelined dispatch: the returned searcher *enqueues* the replicated
executable and returns unmaterialized device arrays — the batcher's
completion thread is the only place that blocks — so at
``pipeline_depth`` > 1 the host shards/pads the next batch while the
mesh still computes the previous ones, with the same bounded in-flight
window as the single-chip path.

Shape discipline: query shards are ``bucket/size`` rows, so warming the
bucket ladder warms the replicated executables too — one compile per
bucket, independent of device count.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu import obs
from raft_tpu.comms.comms import Comms, local_comms
from raft_tpu.core.compat import shard_map
from raft_tpu.core.trace import trace_range
from raft_tpu.serve.registry import IndexRegistry


def make_replicated_search(comms: Comms, search_fn):
    """Build a reusable ``(queries, k) -> (distances, ids)`` replicated
    searcher around ``search_fn(queries_shard, k)``.

    ``search_fn`` must be traceable given a [q_shard, dim] query array
    (all index state enters as closure constants — every backend search
    and ``MutableIndex.search`` qualify).  Queries are padded to a
    multiple of the axis size; padded rows are dropped from the result.

    The returned callable owns its executables: the shard_map body is
    wrapped in a persistent ``jax.jit`` per k, so repeated calls at the
    same (k, padded batch) shape reuse one compile — the zero-recompile
    contract the batcher's warmup ladder relies on.  Build it ONCE per
    index state (the serve path keys it on registry version + mutation
    generation) and call it many times.
    """
    mesh, axis = comms.mesh, comms.axis
    size = comms.get_size()
    # the per-shard search runs under jit, not bare in the shard_map body:
    # older jax's ShardMapTracer lacks the eager operator surface (bitwise
    # ops on closure constants fail), while nested-jit tracers are complete
    jitted = jax.jit(search_fn, static_argnums=1)
    sharded = {}  # k -> jitted shard_map wrapper

    def _sharded(k: int):
        f = sharded.get(k)
        if f is None:

            def local(q_shard):
                v, i = jitted(q_shard, k)
                vg = lax.all_gather(v, axis, axis=0, tiled=True)
                ig = lax.all_gather(i, axis, axis=0, tiled=True)
                return vg, ig

            f = jax.jit(
                shard_map(
                    local,
                    mesh=mesh,
                    in_specs=(P(axis, None),),
                    out_specs=(P(None, None), P(None, None)),
                    check_vma=False,
                )
            )
            sharded[k] = f
        return f

    query_spec = NamedSharding(mesh, P(axis, None))

    def _pre_sharded(queries) -> bool:
        # the batcher's staging buffers (or a caller that device_put its
        # own shards) may hand us queries already laid out P(axis, None);
        # a fresh device_put then shows up as a pointless copy_out/shard
        # stage in the flight recorder — detect and skip it
        if not isinstance(queries, jax.Array) or queries.ndim != 2:
            return False
        if queries.dtype != jnp.float32 or queries.shape[0] % size != 0:
            return False
        try:
            return queries.sharding.is_equivalent_to(query_spec, queries.ndim)
        except Exception:
            return False

    def run(queries, k: int) -> Tuple[jax.Array, jax.Array]:
        if _pre_sharded(queries):
            q = queries.shape[0]
            t0 = time.perf_counter()
            qs = queries
        else:
            queries = jnp.asarray(queries, jnp.float32)
            q = queries.shape[0]
            q_pad = -(-q // size) * size
            if q_pad != q:
                queries = jnp.pad(queries, ((0, q_pad - q), (0, 0)))
            t0 = time.perf_counter()
            qs = jax.device_put(queries, query_spec)
        with trace_range("serve.replicated_search") as sp:
            t1 = time.perf_counter()
            v, i = _sharded(k)(qs)
            t2 = time.perf_counter()
            if sp is not None:
                # shard: host-side pad + device_put of the query shards;
                # dispatch: tracing/enqueue of the replicated executable
                # (device wait lands in the caller's block_until_ready)
                sp.add_stage("shard", t1 - t0)
                sp.add_stage("dispatch", t2 - t1)
        return v[:q], i[:q]

    return run


def replicated_search(
    comms: Comms,
    search_fn,
    queries: jax.Array,
    k: int,
) -> Tuple[jax.Array, jax.Array]:
    """One-shot convenience over :func:`make_replicated_search`.

    Compiles fresh every call — for repeated serving use
    ``make_replicated_search`` (or :class:`ReplicaGroup`, which caches).
    Returns replicated (distances [q, k], ids [q, k]).
    """
    return make_replicated_search(comms, search_fn)(queries, k)


class ReplicaGroup:
    """A registry served data-parallel across the local mesh.

    Resolves names through the registry *per call* (so hot-swaps apply to
    the next batch) and runs the resolved index's merged mutable search
    replicated over the comms axis.  Drop-in as a batcher ``search_fn``
    via :meth:`searcher`.

    Two scaling modes share this front end:

    - ``shard_index=False`` (default): query sharding — every device holds
      the full index, queries split ``P(axis, None)``.  N devices ≈ N×
      throughput; capacity capped by one chip's HBM.
    - ``shard_index=True``: index sharding — registry indexes are
      partitioned across the axis via
      :class:`~raft_tpu.serve.shard.ShardedIndex` (capacity ≈ N× one
      chip), queries replicate, and one cross-shard merge produces the
      global top-k.  An index that is *already* a ``ShardedIndex`` is
      dispatched directly in either mode.
    """

    def __init__(
        self,
        registry: IndexRegistry,
        comms: Optional[Comms] = None,
        *,
        n_devices: Optional[int] = None,
        shard_index: bool = False,
    ):
        self.registry = registry
        self.comms = comms if comms is not None else local_comms(n_devices)
        self.shard_index = shard_index
        # per-name replicated searcher, keyed on (version, generation) so
        # hot-swaps and mutations retrace while steady-state traffic reuses
        # the warmed executables (zero hot-path recompiles)
        self._searchers = {}

    @property
    def n_replicas(self) -> int:
        return self.comms.get_size()

    def search(
        self, name: str, queries, k: int
    ) -> Tuple[jax.Array, jax.Array]:
        from raft_tpu.serve.shard import ShardedIndex

        index, version = self.registry.get_versioned(name)
        key = (version, getattr(index, "generation", 0))
        cached = self._searchers.get(name)
        if cached is None or cached[0] != key:
            if isinstance(index, ShardedIndex):
                # already partitioned (and pinned to its own mesh) — the
                # cross-shard merge is baked into its search
                run = index.search
            elif self.shard_index:
                run = ShardedIndex.from_index(
                    index, self.comms, label=name
                ).search
            else:
                run = make_replicated_search(
                    self.comms, lambda q_shard, kk: index.search(q_shard, kk)
                )
            self._searchers[name] = cached = (key, run)
            # every rebuild retraces the replicated executables on next
            # dispatch — a counter climbing on the hot path is the
            # "swap/mutation churn is eating compiles" capacity signal
            obs.default_registry().counter(
                "raft_tpu_replica_searcher_builds_total",
                help="replicated searcher (re)builds, one per index "
                "version/generation change",
            ).inc(index=name)
            # a point event in the flight ring: an incident dump shows the
            # rebuild (and its retrace cost) next to the batches it delayed
            obs.flight.record_event(
                "replica_rebuild", index=name,
                version=key[0], generation=key[1],
            )
        return cached[1](queries, k)

    def searcher(self, name: str, k: int):
        """A ``queries -> (distances, ids)`` callable for MicroBatcher."""

        def search_fn(queries):
            return self.search(name, queries, k)

        return search_fn

    def member_searchers(self, name: str, k: int):
        """Two independently-dispatched searchers for hedged dispatch
        (:class:`~raft_tpu.serve.overload.HedgedDispatcher`): the
        replicated mesh search as the primary, and a direct single-chip
        search resolved against the same registry as the hedge.  The two
        run genuinely different executables — if the collective path
        stalls (a straggling replica, a slow all-gather), the local
        member still answers from one chip.  On a multi-host deployment
        the hedge member would instead target a second replica group on
        another slice; the host-side contract (same signature, distinct
        dispatch) is identical.
        """

        def local_fn(queries):
            index, _version = self.registry.get_versioned(name)
            return index.search(queries, k)

        return (self.searcher(name, k), local_fn)
