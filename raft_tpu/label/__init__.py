"""Label utilities (ref: cpp/include/raft/label/)."""

from raft_tpu.label.classlabels import get_classlabels, make_monotonic, relabel
from raft_tpu.label.merge_labels import merge_labels

__all__ = ["get_classlabels", "make_monotonic", "relabel", "merge_labels"]
