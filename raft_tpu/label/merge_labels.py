"""Merge two label arrays over a shared mask (ref: label/merge_labels.cuh —
union-find-flavored merge used by multi-batch clustering (DBSCAN-style):
groups of ``labels_a`` are unioned with groups of ``labels_b`` wherever the
two co-occur on masked rows, and every row adopts its union root.

TPU re-design: the reference runs an iterative device union-find with
atomics. Here the same fixpoint is reached with label propagation using
segment-min + pointer jumping — the same machinery as
sparse.solver.connected_components. Internally labels are kept as *root row
ids* (always ≤ the row's own id), which makes the pointer-jump provably
terminating; the result maps each row to the min row id of its merged group.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_INT_MAX = jnp.iinfo(jnp.int32).max


@jax.jit
def merge_labels(labels_a: jax.Array, labels_b: jax.Array, mask: jax.Array) -> jax.Array:
    """Union a-groups (all rows) with b-groups (masked rows). Returns [n]
    int32: min row id of each row's merged group."""
    a = jnp.asarray(labels_a, jnp.int32)
    b = jnp.asarray(labels_b, jnp.int32)
    mask = jnp.asarray(mask, bool)
    n = a.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)

    def dense_groups(labels, live):
        """Relabel arbitrary int labels to dense ids in [0, n) (dead rows →
        n) — labels may exceed n, so they cannot index segment arrays
        directly."""
        order = jnp.argsort(jnp.where(live, labels, jnp.iinfo(jnp.int32).max),
                            stable=True)
        s = labels[order]
        first = jnp.concatenate([jnp.ones(1, bool), s[1:] != s[:-1]])
        gid = jnp.cumsum(first) - 1
        out = jnp.zeros(n, jnp.int32).at[order].set(gid.astype(jnp.int32))
        return jnp.where(live, out, n)

    ga = dense_groups(a, jnp.ones(n, bool))
    gb = dense_groups(b, mask)

    # init: every row → min row id of its a-group (≤ own id, so the
    # pointer-jump below strictly descends and must terminate)
    ra = jax.ops.segment_min(rows, ga, num_segments=n + 1)[:n]
    cur0 = ra[ga]

    def cond(state):
        lab, changed = state
        return changed

    def body(state):
        cur, _ = state
        mina = jax.ops.segment_min(cur, ga, num_segments=n + 1)[:n]
        minb = jax.ops.segment_min(
            jnp.where(mask, cur, _INT_MAX), gb, num_segments=n + 1
        )[:n]
        upd = jnp.minimum(
            mina[ga], jnp.where(mask, minb[gb % jnp.asarray(n, jnp.int32)], cur)
        )
        new = jnp.minimum(cur, upd)

        def jump_cond(p):
            return jnp.any(p[p] != p)

        new = lax.while_loop(jump_cond, lambda p: p[p], jnp.minimum(new, new[new]))
        return new, jnp.any(new != cur)

    lab, _ = lax.while_loop(cond, body, (cur0, jnp.asarray(True)))
    return lab
