"""Class-label utilities (ref: label/classlabels.cuh — getUniquelabels,
getOvrlabels, make_monotonic)."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def get_classlabels(labels: jax.Array) -> jax.Array:
    """Sorted unique labels (ref: classlabels.cuh getUniquelabels).
    Host-compacted (result size is data-dependent)."""
    return jnp.asarray(np.unique(np.asarray(labels)))


def make_monotonic(labels: jax.Array, *, classes: jax.Array = None) -> jax.Array:
    """Map labels onto 0..k−1 preserving sorted order
    (ref: classlabels.cuh make_monotonic)."""
    labels = jnp.asarray(labels)
    if classes is None:
        classes = get_classlabels(labels)
    else:
        classes = jnp.asarray(classes)
    return jnp.searchsorted(classes, labels).astype(jnp.int32)


def relabel(labels: jax.Array, old: jax.Array, new: jax.Array) -> jax.Array:
    """Replace each occurrence of old[i] with new[i] (ref: getOvrlabels-style
    relabelling used by one-vs-rest pipelines)."""
    labels = jnp.asarray(labels)
    out = labels
    for o, v in zip(np.asarray(old).tolist(), np.asarray(new).tolist()):
        out = jnp.where(labels == o, v, out)
    return out
