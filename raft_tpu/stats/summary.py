"""Summary statistics (ref: raft/stats/{mean,meanvar,stddev,minmax,cov,
histogram,weighted_mean,mean_center,dispersion}.cuh). All are plain XLA
reductions — the reference's custom kernels exist only because CUDA needs
hand-written reductions; TPU gets them from the compiler.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def mean(m: jax.Array, *, axis: int = 0) -> jax.Array:
    return jnp.mean(m, axis=axis)


def mean_center(m: jax.Array, *, axis: int = 0) -> jax.Array:
    return m - jnp.mean(m, axis=axis, keepdims=True)


def meanvar(m: jax.Array, *, axis: int = 0, sample: bool = True) -> Tuple[jax.Array, jax.Array]:
    mu = jnp.mean(m, axis=axis)
    var = jnp.var(m, axis=axis, ddof=1 if sample else 0)
    return mu, var


def stddev(m: jax.Array, *, axis: int = 0, sample: bool = True) -> jax.Array:
    return jnp.std(m, axis=axis, ddof=1 if sample else 0)


def minmax(m: jax.Array, *, axis: int = 0) -> Tuple[jax.Array, jax.Array]:
    return jnp.min(m, axis=axis), jnp.max(m, axis=axis)


def cov(m: jax.Array, *, sample: bool = True, centered: bool = False) -> jax.Array:
    """Column covariance matrix (ref: stats/cov.cuh) — one MXU gemm."""
    x = m if centered else mean_center(m, axis=0)
    n = m.shape[0]
    denom = (n - 1) if sample else n
    return (x.T @ x) / denom


def histogram(m: jax.Array, n_bins: int, *, lo: float, hi: float) -> jax.Array:
    """Per-column histogram (ref: stats/histogram.cuh)."""
    m2 = m if m.ndim == 2 else m[:, None]
    scaled = (m2 - lo) / (hi - lo) * n_bins
    bins = jnp.clip(scaled.astype(jnp.int32), 0, n_bins - 1)
    out = jax.vmap(
        lambda col: jnp.zeros((n_bins,), jnp.int32).at[col].add(1), in_axes=1, out_axes=1
    )(bins)
    return out


def weighted_mean(m: jax.Array, weights: jax.Array, *, axis: int = 0) -> jax.Array:
    if axis == 0:
        return jnp.sum(m * weights[:, None], axis=0) / jnp.sum(weights)
    return jnp.sum(m * weights[None, :], axis=1) / jnp.sum(weights)


def dispersion(
    centroids: jax.Array, cluster_sizes: jax.Array, *, global_centroid: Optional[jax.Array] = None
) -> jax.Array:
    """Between-cluster dispersion (ref: stats/dispersion.cuh)."""
    n = jnp.sum(cluster_sizes)
    if global_centroid is None:
        global_centroid = jnp.sum(centroids * cluster_sizes[:, None], axis=0) / n
    d2 = jnp.sum((centroids - global_centroid[None, :]) ** 2, axis=1)
    return jnp.sqrt(jnp.sum(cluster_sizes * d2) / n)
