"""Model metrics (ref: cpp/include/raft/stats/ — accuracy.cuh, r2_score.cuh,
regression_metrics.cuh, neighborhood_recall.cuh, silhouette_score.cuh,
adjusted_rand_index.cuh, rand_index.cuh, entropy.cuh, mutual_info_score.cuh,
completeness_score.cuh, homogeneity_score.cuh, v_measure.cuh,
contingency_matrix.cuh, kl_divergence.cuh, trustworthiness_score.cuh,
information_criterion.cuh).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.distance.pairwise import distance_matrix_tile


def accuracy(pred: jax.Array, ref: jax.Array) -> jax.Array:
    return jnp.mean((jnp.asarray(pred) == jnp.asarray(ref)).astype(jnp.float32))


def r2_score(y: jax.Array, y_hat: jax.Array) -> jax.Array:
    y = jnp.asarray(y, jnp.float32)
    y_hat = jnp.asarray(y_hat, jnp.float32)
    ss_res = jnp.sum((y - y_hat) ** 2)
    ss_tot = jnp.sum((y - jnp.mean(y)) ** 2)
    return 1.0 - ss_res / ss_tot


def regression_metrics(pred: jax.Array, ref: jax.Array) -> Dict[str, jax.Array]:
    """(ref: stats/regression_metrics.cuh — mean abs / mean sq / median abs)"""
    pred = jnp.asarray(pred, jnp.float32)
    ref = jnp.asarray(ref, jnp.float32)
    err = pred - ref
    return {
        "mean_abs_error": jnp.mean(jnp.abs(err)),
        "mean_squared_error": jnp.mean(err * err),
        "median_abs_error": jnp.median(jnp.abs(err)),
    }


def recall_at_k(indices, ref_indices, k: Optional[int] = None) -> float:
    """Canonical host-side recall@k — THE recall every consumer shares.

    Order-insensitive set-intersection recall, the reference's ANN
    evaluation metric (ref: stats/neighborhood_recall.cuh;
    cpp/test/neighbors/ann_utils.cuh:128 calc_recall): the fraction of
    reference neighbors recovered anywhere in the served top-k.  Negative
    reference ids (padding / pruned slots) are excluded from the
    denominator.  Pure numpy on purpose: the obs quality auditor calls
    this from a background thread while serving traffic, where a stray
    jnp dispatch would (a) race the serve recompile attribution bracket
    and (b) contend for the device.  ``k`` truncates both sides (default:
    the smaller of the two widths).
    """
    ids = np.asarray(indices)
    ref = np.asarray(ref_indices)
    if ids.ndim != 2 or ref.ndim != 2 or ids.shape[0] != ref.shape[0]:
        raise ValueError(
            f"expected [rows, k] id matrices, got {ids.shape} vs {ref.shape}"
        )
    if k is None:
        k = min(ids.shape[1], ref.shape[1])
    ids = ids[:, :k]
    ref = ref[:, :k]
    valid = ref >= 0
    if not valid.any():
        return 0.0
    match = (ids[:, :, None] == ref[:, None, :]).any(axis=1)
    return float((match & valid).sum() / valid.sum())


def tie_aware_recall_at_k(
    distances, ref_distances, k: Optional[int] = None,
    *, eps: float = 1e-4, select_min: bool = True,
) -> float:
    """Distance-based recall that forgives ties at the k-th boundary.

    An index returning a different-but-equidistant neighbor is not wrong;
    id-set recall (:func:`recall_at_k`) still penalizes it.  This variant
    counts a served neighbor as correct when its distance is within a
    relative ``eps`` of the row's k-th reference distance (ann-benchmarks'
    epsilon-recall).  ``select_min=False`` flips the comparison for
    similarity metrics (inner product) where larger is better.
    """
    d = np.asarray(distances, dtype=np.float64)
    rd = np.asarray(ref_distances, dtype=np.float64)
    if d.ndim != 2 or rd.ndim != 2 or d.shape[0] != rd.shape[0]:
        raise ValueError(
            f"expected [rows, k] distance matrices, got {d.shape} vs {rd.shape}"
        )
    if k is None:
        k = min(d.shape[1], rd.shape[1])
    d = d[:, :k]
    thresh = rd[:, k - 1 : k]  # row-wise k-th best reference distance
    tol = eps * np.maximum(np.abs(thresh), 1.0)
    ok = d <= thresh + tol if select_min else d >= thresh - tol
    return float(ok.mean())


def rank_displacement(indices, ref_indices, k: Optional[int] = None) -> float:
    """Mean |served rank − true rank| of the reference neighbors.

    Recall sees *whether* a true neighbor appears; displacement sees
    *where* — an index that always ranks the true nearest neighbor 9th
    holds recall@10 = 1.0 while this metric reads ~8.  A reference
    neighbor missing from the served list costs ``k`` (the worst possible
    displacement), so the value degrades smoothly into recall loss.
    Negative reference ids are excluded.  0.0 is perfect.
    """
    ids = np.asarray(indices)
    ref = np.asarray(ref_indices)
    if ids.ndim != 2 or ref.ndim != 2 or ids.shape[0] != ref.shape[0]:
        raise ValueError(
            f"expected [rows, k] id matrices, got {ids.shape} vs {ref.shape}"
        )
    if k is None:
        k = min(ids.shape[1], ref.shape[1])
    ids = ids[:, :k]
    ref = ref[:, :k]
    eq = ids[:, :, None] == ref[:, None, :]        # [rows, k_served, k_ref]
    pos = np.argmax(eq, axis=1)                    # first match (0 if none)
    found = eq.any(axis=1)
    ideal = np.arange(k)[None, :]
    disp = np.where(found, np.abs(pos - ideal), k)
    valid = ref >= 0
    if not valid.any():
        return 0.0
    return float(disp[valid].mean())


def neighborhood_recall(indices: jax.Array, ref_indices: jax.Array) -> jax.Array:
    """Device-side (jit-capable) variant of :func:`recall_at_k`.

    Same set-intersection semantics; stays jnp so it can run inside a
    traced computation.  Host-side consumers (bench, the quality auditor)
    use :func:`recall_at_k` directly."""
    indices = jnp.asarray(indices)
    ref_indices = jnp.asarray(ref_indices)
    match = (indices[:, :, None] == ref_indices[:, None, :]).any(axis=1)
    return jnp.mean(match.astype(jnp.float32))


def contingency_matrix(a: jax.Array, b: jax.Array, n_classes: Optional[int] = None) -> jax.Array:
    """(ref: stats/contingency_matrix.cuh)"""
    a = jnp.asarray(a, jnp.int32)
    b = jnp.asarray(b, jnp.int32)
    if n_classes is None:
        n_classes = int(max(int(jnp.max(a)), int(jnp.max(b))) + 1)
    flat = a * n_classes + b
    counts = jnp.zeros((n_classes * n_classes,), jnp.int32).at[flat].add(1)
    return counts.reshape(n_classes, n_classes)


def entropy(labels: jax.Array, n_classes: Optional[int] = None) -> jax.Array:
    labels = jnp.asarray(labels, jnp.int32)
    if n_classes is None:
        n_classes = int(jnp.max(labels)) + 1
    counts = jnp.zeros((n_classes,), jnp.float32).at[labels].add(1.0)
    p = counts / labels.shape[0]
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(jnp.maximum(p, 1e-30)), 0.0))


def mutual_info_score(a: jax.Array, b: jax.Array, n_classes: Optional[int] = None) -> jax.Array:
    cm = contingency_matrix(a, b, n_classes).astype(jnp.float32)
    n = jnp.sum(cm)
    pij = cm / n
    pi = jnp.sum(pij, axis=1, keepdims=True)
    pj = jnp.sum(pij, axis=0, keepdims=True)
    ratio = pij / jnp.maximum(pi * pj, 1e-30)
    return jnp.sum(jnp.where(pij > 0, pij * jnp.log(jnp.maximum(ratio, 1e-30)), 0.0))


def homogeneity_score(truth: jax.Array, pred: jax.Array, n_classes: Optional[int] = None) -> jax.Array:
    mi = mutual_info_score(truth, pred, n_classes)
    h = entropy(truth, n_classes)
    return jnp.where(h > 0, mi / jnp.maximum(h, 1e-30), 1.0)


def completeness_score(truth: jax.Array, pred: jax.Array, n_classes: Optional[int] = None) -> jax.Array:
    return homogeneity_score(pred, truth, n_classes)


def v_measure(truth: jax.Array, pred: jax.Array, n_classes: Optional[int] = None, beta: float = 1.0) -> jax.Array:
    h = homogeneity_score(truth, pred, n_classes)
    c = completeness_score(truth, pred, n_classes)
    denom = beta * h + c
    return jnp.where(denom > 0, (1 + beta) * h * c / jnp.maximum(denom, 1e-30), 0.0)


def rand_index(a: jax.Array, b: jax.Array, n_classes: Optional[int] = None) -> jax.Array:
    cm = contingency_matrix(a, b, n_classes).astype(jnp.float32)
    n = jnp.sum(cm)
    comb = lambda x: x * (x - 1) / 2
    sum_ij = jnp.sum(comb(cm))
    sum_i = jnp.sum(comb(jnp.sum(cm, axis=1)))
    sum_j = jnp.sum(comb(jnp.sum(cm, axis=0)))
    total = comb(n)
    # RI = (agreements) / total pairs
    return (total + 2 * sum_ij - sum_i - sum_j) / total


def adjusted_rand_index(a: jax.Array, b: jax.Array, n_classes: Optional[int] = None) -> jax.Array:
    cm = contingency_matrix(a, b, n_classes).astype(jnp.float32)
    n = jnp.sum(cm)
    comb = lambda x: x * (x - 1) / 2
    sum_ij = jnp.sum(comb(cm))
    sum_i = jnp.sum(comb(jnp.sum(cm, axis=1)))
    sum_j = jnp.sum(comb(jnp.sum(cm, axis=0)))
    expected = sum_i * sum_j / jnp.maximum(comb(n), 1e-30)
    max_idx = 0.5 * (sum_i + sum_j)
    return (sum_ij - expected) / jnp.maximum(max_idx - expected, 1e-30)


def kl_divergence(p: jax.Array, q: jax.Array) -> jax.Array:
    p = jnp.asarray(p, jnp.float32)
    q = jnp.asarray(q, jnp.float32)
    return jnp.sum(jnp.where(p > 0, p * jnp.log(jnp.maximum(p, 1e-30) / jnp.maximum(q, 1e-30)), 0.0))


def silhouette_score(
    x: jax.Array, labels: jax.Array, n_clusters: Optional[int] = None, *, metric: str = "euclidean"
) -> jax.Array:
    """Mean silhouette coefficient (ref: stats/silhouette_score.cuh)."""
    x = jnp.asarray(x, jnp.float32)
    labels = jnp.asarray(labels, jnp.int32)
    if n_clusters is None:
        n_clusters = int(jnp.max(labels)) + 1
    n = x.shape[0]
    d = distance_matrix_tile(x, x, metric)
    onehot = jax.nn.one_hot(labels, n_clusters, dtype=jnp.float32)  # [n, k]
    counts = jnp.sum(onehot, axis=0)  # [k]
    # per-point sum of distances to each cluster: [n, k]
    sums = d @ onehot
    same = jnp.take_along_axis(sums, labels[:, None], axis=1)[:, 0]
    own_count = counts[labels]
    a = jnp.where(own_count > 1, same / jnp.maximum(own_count - 1, 1), 0.0)
    mean_other = sums / jnp.maximum(counts[None, :], 1)
    # mask own cluster AND empty clusters (whose mean would read as 0)
    mean_other = jnp.where(
        jax.nn.one_hot(labels, n_clusters, dtype=bool) | (counts[None, :] == 0),
        jnp.inf,
        mean_other,
    )
    b = jnp.min(mean_other, axis=1)
    s = jnp.where(own_count > 1, (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30), 0.0)
    return jnp.mean(s)


def trustworthiness(
    x: jax.Array, x_embedded: jax.Array, n_neighbors: int, *, metric: str = "euclidean"
) -> jax.Array:
    """Trustworthiness of an embedding (ref: stats/trustworthiness_score.cuh)."""
    x = jnp.asarray(x, jnp.float32)
    e = jnp.asarray(x_embedded, jnp.float32)
    n = x.shape[0]
    d_orig = distance_matrix_tile(x, x, metric).at[jnp.arange(n), jnp.arange(n)].set(jnp.inf)
    d_emb = distance_matrix_tile(e, e, metric).at[jnp.arange(n), jnp.arange(n)].set(jnp.inf)
    rank_orig = jnp.argsort(jnp.argsort(d_orig, axis=1), axis=1)  # 0 = nearest
    nn_emb = jnp.argsort(d_emb, axis=1)[:, :n_neighbors]
    r = jnp.take_along_axis(rank_orig, nn_emb, axis=1)  # ranks in original space
    penalty = jnp.sum(jnp.maximum(r - n_neighbors + 1, 0))
    norm = 2.0 / (n * n_neighbors * (2 * n - 3 * n_neighbors - 1))
    return 1.0 - norm * penalty


def information_criterion(
    log_likelihood: jax.Array, n_params: int, n_samples: int, *, criterion: str = "aic"
) -> jax.Array:
    """(ref: stats/information_criterion.cuh — AIC/AICc/BIC)"""
    ll = jnp.asarray(log_likelihood, jnp.float32)
    if criterion == "aic":
        return -2.0 * ll + 2.0 * n_params
    if criterion == "aicc":
        return -2.0 * ll + 2.0 * n_params + (2.0 * n_params * (n_params + 1)) / max(
            n_samples - n_params - 1, 1
        )
    if criterion == "bic":
        return -2.0 * ll + n_params * jnp.log(float(n_samples))
    raise ValueError(criterion)
