"""Bitset over uint32 words — ANN pre-filtering support.

TPU-native analog of ``raft::core::bitset`` (ref:
cpp/include/raft/core/bitset.hpp:36-225): a device bitset with
test/set/flip/count used as a query-time sample filter by the ANN indexes
(ref: cpp/include/raft/neighbors/sample_filter_types.hpp:27-73
``bitset_filter``). Functional: every mutator returns a new words array;
the class is a thin pytree-friendly wrapper.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

WORD_BITS = 32


def _n_words(n_bits: int) -> int:
    return (n_bits + WORD_BITS - 1) // WORD_BITS


@jax.tree_util.register_pytree_node_class
class Bitset:
    """Fixed-size bitset stored as packed uint32 words."""

    def __init__(self, words: jax.Array, n_bits: int):
        self.words = words
        self.n_bits = n_bits

    # pytree protocol so Bitset can cross jit boundaries
    def tree_flatten(self):
        return (self.words,), self.n_bits

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux)

    @classmethod
    def create(cls, n_bits: int, default: bool = True) -> "Bitset":
        fill = jnp.uint32(0xFFFFFFFF) if default else jnp.uint32(0)
        return cls(jnp.full((_n_words(n_bits),), fill, dtype=jnp.uint32), n_bits)

    @classmethod
    def from_mask(cls, mask: jax.Array) -> "Bitset":
        """Pack a boolean vector into a bitset."""
        n_bits = mask.shape[0]
        nw = _n_words(n_bits)
        padded = jnp.zeros((nw * WORD_BITS,), dtype=jnp.uint32).at[:n_bits].set(
            mask.astype(jnp.uint32)
        )
        shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)
        words = jnp.sum(padded.reshape(nw, WORD_BITS) << shifts[None, :], axis=1, dtype=jnp.uint32)
        return cls(words, n_bits)

    def test(self, idx: jax.Array) -> jax.Array:
        """Elementwise membership test; idx any integer shape -> bool array."""
        idx = jnp.asarray(idx)
        word = self.words[idx // WORD_BITS]
        return ((word >> (idx % WORD_BITS).astype(jnp.uint32)) & 1).astype(bool)

    def set(self, idx: jax.Array, value: bool = True) -> "Bitset":
        # Scatter through a boolean mask: duplicate indices in one call (or
        # several indices landing in the same word) must all take effect, and
        # .at[w].set on words is last-write-wins for duplicate words.
        idx = jnp.atleast_1d(jnp.asarray(idx))
        touched = Bitset.from_mask(
            jnp.zeros((self.n_bits,), bool).at[idx].set(True)
        ).words
        if value:
            words = self.words | touched
        else:
            words = self.words & ~touched
        return Bitset(words, self.n_bits)

    def flip(self) -> "Bitset":
        return Bitset(~self.words, self.n_bits)

    def count(self) -> jax.Array:
        """Population count (ref: bitset.hpp count / util/popc.cuh)."""
        # mask tail bits beyond n_bits
        nw = self.words.shape[0]
        tail_bits = self.n_bits - (nw - 1) * WORD_BITS
        tail_mask = (
            jnp.uint32(0xFFFFFFFF)
            if tail_bits == WORD_BITS
            else jnp.uint32((1 << tail_bits) - 1)
        )
        masked = self.words.at[-1].set(self.words[-1] & tail_mask)
        x = masked
        # SWAR popcount on uint32 lanes
        x = x - ((x >> 1) & jnp.uint32(0x55555555))
        x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
        x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
        per_word = (x * jnp.uint32(0x01010101)) >> 24
        return jnp.sum(per_word.astype(jnp.int32))

    def to_mask(self) -> jax.Array:
        """Unpack into a boolean vector of length n_bits."""
        shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)
        bits = (self.words[:, None] >> shifts[None, :]) & 1
        return bits.reshape(-1)[: self.n_bits].astype(bool)


def _popcount_words(words: jax.Array) -> jax.Array:
    """SWAR popcount per uint32 lane (any shape) → int32 counts."""
    x = words
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return ((x * jnp.uint32(0x01010101)) >> 24).astype(jnp.int32)


@jax.tree_util.register_pytree_node_class
class RowFilter:
    """Per-query-row pass filters packed as uint32 words: ``words[r]`` is
    the pass bitset applied to query row ``r``.

    The ragged serving path packs requests with heterogeneous predicate
    bitsets into one batch; shipping the filter as a ``[rows, n_words]``
    operand keeps the filter mix out of the compiled shape — any
    combination of predicates reuses the one executable per capacity
    bucket.  ``fid``/``table`` optionally carry the descriptor form
    (per-row filter id into a ``[n_filters, n_words]`` table) for kernels
    that prefer the indirect layout (kernels/ivf_scan's query-major leg
    prefetches fid and gathers the table block per grid step).

    ``pass_count`` is a host-int lower bound on the number of passing ids
    in any row; heuristics that size work buffers from filter selectivity
    (cagra's itopk widening) read it via :meth:`count` so the traffic mix
    never feeds back into compiled shapes.
    """

    def __init__(
        self,
        words: jax.Array,
        n_bits: int,
        *,
        fid: Optional[jax.Array] = None,
        table: Optional[jax.Array] = None,
        pass_count: Optional[int] = None,
    ):
        self.words = words
        self.n_bits = n_bits
        self.fid = fid
        self.table = table
        self.pass_count = pass_count

    def tree_flatten(self):
        return (self.words, self.fid, self.table), (self.n_bits, self.pass_count)

    @classmethod
    def tree_unflatten(cls, aux, children):
        words, fid, table = children
        return cls(words, aux[0], fid=fid, table=table, pass_count=aux[1])

    @classmethod
    def from_mask_rows(cls, masks: jax.Array) -> "RowFilter":
        """Pack a boolean [rows, n_bits] matrix into per-row word sets."""
        rows, n_bits = masks.shape
        nw = _n_words(n_bits)
        padded = (
            jnp.zeros((rows, nw * WORD_BITS), dtype=jnp.uint32)
            .at[:, :n_bits]
            .set(masks.astype(jnp.uint32))
        )
        shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)
        words = jnp.sum(
            padded.reshape(rows, nw, WORD_BITS) << shifts[None, None, :],
            axis=2,
            dtype=jnp.uint32,
        )
        return cls(words, n_bits)

    @classmethod
    def from_table(
        cls,
        table: jax.Array,
        fid,
        n_bits: int,
        *,
        pass_count: Optional[int] = None,
    ) -> "RowFilter":
        """Build from a filter table [n_filters, n_words] + per-row ids.

        The gather runs host-side (numpy) when given numpy inputs so filter
        registration never becomes a traced shape: ``table`` grows with the
        filter population while ``words`` stays [rows, n_words].
        """
        import numpy as np

        if isinstance(table, np.ndarray):
            words = jnp.asarray(table[np.asarray(fid)])
        else:
            words = jnp.asarray(table)[jnp.asarray(fid)]
        return cls(
            words,
            n_bits,
            fid=jnp.asarray(fid, jnp.int32),
            table=jnp.asarray(table),
            pass_count=pass_count,
        )

    def test_rows(self, ids: jax.Array) -> jax.Array:
        """Per-row membership test: ids [rows, ...] → bool of ids.shape."""
        ids = jnp.asarray(ids)
        r = ids.shape[0]
        flat = (jnp.clip(ids, 0, None) // WORD_BITS).reshape(r, -1)
        word = jnp.take_along_axis(self.words, flat, axis=1).reshape(ids.shape)
        bit = (word >> (jnp.clip(ids, 0, None) % WORD_BITS).astype(jnp.uint32)) & 1
        return bit.astype(bool)

    def count(self):
        """Minimum per-row passing population (host int when pass_count is
        pinned, else a traced scalar)."""
        if self.pass_count is not None:
            return self.pass_count
        nw = self.words.shape[1]
        tail_bits = self.n_bits - (nw - 1) * WORD_BITS
        tail_mask = (
            jnp.uint32(0xFFFFFFFF)
            if tail_bits == WORD_BITS
            else jnp.uint32((1 << tail_bits) - 1)
        )
        masked = self.words.at[:, -1].set(self.words[:, -1] & tail_mask)
        return jnp.min(jnp.sum(_popcount_words(masked), axis=1))
