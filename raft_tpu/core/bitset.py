"""Bitset over uint32 words — ANN pre-filtering support.

TPU-native analog of ``raft::core::bitset`` (ref:
cpp/include/raft/core/bitset.hpp:36-225): a device bitset with
test/set/flip/count used as a query-time sample filter by the ANN indexes
(ref: cpp/include/raft/neighbors/sample_filter_types.hpp:27-73
``bitset_filter``). Functional: every mutator returns a new words array;
the class is a thin pytree-friendly wrapper.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

WORD_BITS = 32


def _n_words(n_bits: int) -> int:
    return (n_bits + WORD_BITS - 1) // WORD_BITS


@jax.tree_util.register_pytree_node_class
class Bitset:
    """Fixed-size bitset stored as packed uint32 words."""

    def __init__(self, words: jax.Array, n_bits: int):
        self.words = words
        self.n_bits = n_bits

    # pytree protocol so Bitset can cross jit boundaries
    def tree_flatten(self):
        return (self.words,), self.n_bits

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux)

    @classmethod
    def create(cls, n_bits: int, default: bool = True) -> "Bitset":
        fill = jnp.uint32(0xFFFFFFFF) if default else jnp.uint32(0)
        return cls(jnp.full((_n_words(n_bits),), fill, dtype=jnp.uint32), n_bits)

    @classmethod
    def from_mask(cls, mask: jax.Array) -> "Bitset":
        """Pack a boolean vector into a bitset."""
        n_bits = mask.shape[0]
        nw = _n_words(n_bits)
        padded = jnp.zeros((nw * WORD_BITS,), dtype=jnp.uint32).at[:n_bits].set(
            mask.astype(jnp.uint32)
        )
        shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)
        words = jnp.sum(padded.reshape(nw, WORD_BITS) << shifts[None, :], axis=1, dtype=jnp.uint32)
        return cls(words, n_bits)

    def test(self, idx: jax.Array) -> jax.Array:
        """Elementwise membership test; idx any integer shape -> bool array."""
        idx = jnp.asarray(idx)
        word = self.words[idx // WORD_BITS]
        return ((word >> (idx % WORD_BITS).astype(jnp.uint32)) & 1).astype(bool)

    def set(self, idx: jax.Array, value: bool = True) -> "Bitset":
        # Scatter through a boolean mask: duplicate indices in one call (or
        # several indices landing in the same word) must all take effect, and
        # .at[w].set on words is last-write-wins for duplicate words.
        idx = jnp.atleast_1d(jnp.asarray(idx))
        touched = Bitset.from_mask(
            jnp.zeros((self.n_bits,), bool).at[idx].set(True)
        ).words
        if value:
            words = self.words | touched
        else:
            words = self.words & ~touched
        return Bitset(words, self.n_bits)

    def flip(self) -> "Bitset":
        return Bitset(~self.words, self.n_bits)

    def count(self) -> jax.Array:
        """Population count (ref: bitset.hpp count / util/popc.cuh)."""
        # mask tail bits beyond n_bits
        nw = self.words.shape[0]
        tail_bits = self.n_bits - (nw - 1) * WORD_BITS
        tail_mask = (
            jnp.uint32(0xFFFFFFFF)
            if tail_bits == WORD_BITS
            else jnp.uint32((1 << tail_bits) - 1)
        )
        masked = self.words.at[-1].set(self.words[-1] & tail_mask)
        x = masked
        # SWAR popcount on uint32 lanes
        x = x - ((x >> 1) & jnp.uint32(0x55555555))
        x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
        x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
        per_word = (x * jnp.uint32(0x01010101)) >> 24
        return jnp.sum(per_word.astype(jnp.int32))

    def to_mask(self) -> jax.Array:
        """Unpack into a boolean vector of length n_bits."""
        shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)
        bits = (self.words[:, None] >> shifts[None, :]) & 1
        return bits.reshape(-1)[: self.n_bits].astype(bool)
