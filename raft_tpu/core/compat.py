"""JAX-version compatibility shims.

The repo targets whatever jaxlib the image bakes in, and the config
surface moves between releases.  Every shim here follows the same rule:
try the modern config knob first, fall back to the oldest mechanism that
still works, and fail loudly only when neither can apply (e.g. the
backend is already initialized and the setting cannot take effect).
"""

from __future__ import annotations

import os
import re


def set_host_device_count(n: int) -> None:
    """Request ``n`` virtual CPU devices (multi-device simulation).

    Newer jax exposes this as the ``jax_num_cpu_devices`` config option;
    older releases (like the 0.4.x line this image ships) only honor the
    ``--xla_force_host_platform_device_count`` XLA flag, which is read
    when the CPU backend is created.  Either way this must run before the
    first backend touch (``jax.devices()``/any dispatch) to take effect.
    """
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", n)
        return
    except AttributeError:
        pass
    flags = os.environ.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}"
    ).strip()
    # if the CPU backend already exists the flag cannot apply — surface the
    # mismatch instead of silently running single-device
    try:
        backends = jax._src.xla_bridge._backends
    except Exception:  # pragma: no cover - private API moved
        backends = {}
    if backends and len(jax.devices()) != n:
        raise RuntimeError(
            f"set_host_device_count({n}) called after backend init; "
            f"visible devices: {len(jax.devices())}"
        )


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``.

    Modern jax exports it top-level with a ``check_vma`` knob; the 0.4.x
    line ships it under ``jax.experimental`` where the same knob is named
    ``check_rep``.  Callers use the modern keyword spelling.
    """
    try:
        from jax import shard_map as _sm  # jax >= 0.6
    except ImportError:
        from jax.experimental.shard_map import shard_map as _esm

        return _esm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )
    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=check_vma,
    )
