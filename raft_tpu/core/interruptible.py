"""Cooperative cross-thread cancellation — interruptible parity.

Reference: ``core/interruptible.hpp:41-96`` — every blocking stream sync
checks a per-thread cancellation token that another CPU thread can set;
Python surface in pylibraft ``common/interruptible.pyx``.

TPU shape: JAX's ``block_until_ready`` cannot be interrupted mid-wait, so
the cancellation points are the sync entries themselves: every
``Resources.sync`` / ``Comms.sync_stream`` calls ``check()`` before and
after blocking, raising ``InterruptedError`` if this thread's token was
cancelled. Tokens are native (C++ registry) when the toolchain built the
core, with a pure-Python fallback.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class _PyToken:
    def __init__(self):
        self._flag = threading.Event()

    def cancel(self) -> None:
        self._flag.set()

    @property
    def cancelled(self) -> bool:
        return self._flag.is_set()

    def check(self) -> None:
        # reference semantics: a failed check clears the flag
        if self._flag.is_set():
            self._flag.clear()
            raise InterruptedError("interruptible: cancelled")


_tokens: Dict[int, object] = {}
_lock = threading.Lock()


def get_token(thread_id: Optional[int] = None):
    """This (or another) thread's cancellation token
    (ref: interruptible::get_token)."""
    tid = thread_id if thread_id is not None else threading.get_ident()
    with _lock:
        tok = _tokens.get(tid)
        if tok is None:
            from raft_tpu.core import native

            if thread_id is None and native.available():
                tok = native.InterruptibleToken()
            else:
                tok = _PyToken()
            _tokens[tid] = tok
        return tok


def cancel(thread_id: int) -> None:
    """Cancel another thread's next sync (ref: interruptible::cancel)."""
    # prune entries of dead threads so idents recycled by the OS can't
    # inherit stale tokens and the table stays bounded in pool services
    live = {t.ident for t in threading.enumerate()}
    with _lock:
        for tid in [t for t in _tokens if t not in live]:
            del _tokens[tid]
    get_token(thread_id).cancel()


def check() -> None:
    """Raise InterruptedError if this thread was cancelled
    (ref: interruptible::yield_()). No-op when never cancelled."""
    tid = threading.get_ident()
    with _lock:
        tok = _tokens.get(tid)
    if tok is not None:
        try:
            tok.check()
        except InterruptedError:
            # consumed: drop the entry so the flag can't leak to a future
            # thread that recycles this ident
            with _lock:
                _tokens.pop(tid, None)
            raise
