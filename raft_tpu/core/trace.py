"""Tracing / profiling ranges — nvtx parity for TPU.

The reference wraps every major entry point in an NVTX scoped range with a
dedicated ``raft`` domain (ref: cpp/include/raft/core/nvtx.hpp:49-82, used
at e.g. neighbors/detail/ivf_pq_build.cuh:1687).  The TPU equivalents are

- ``jax.profiler.TraceAnnotation`` — host-side Perfetto trace range, shows
  up in ``jax.profiler.trace`` captures (the "domain" maps to the
  ``raft_tpu.`` prefix);
- ``jax.named_scope`` — attaches the name to the HLO ops traced under the
  range so device-side work is attributable in the profile;
- a :mod:`raft_tpu.obs` span — the queryable record: every range reports
  wall time into the metrics registry and becomes the attribution point
  for XLA compile/cache/transfer events, with no profiler attached.

All three are near-zero-cost when nothing is listening; the obs span adds
one histogram record per call (bounded by ``tests/test_obs.py``'s
overhead guard).
"""

from __future__ import annotations

import contextlib
import functools
from typing import Callable, Optional, TypeVar

import jax

from raft_tpu.core import env as _env

DOMAIN = "raft_tpu"

F = TypeVar("F", bound=Callable)

_spans = None  # lazy: raft_tpu.obs pulls numpy/logger machinery not needed
               # by pure-trace consumers until the first range actually opens


def _obs_spans():
    global _spans
    if _spans is None:
        from raft_tpu.obs import spans

        _spans = spans
    return _spans


@contextlib.contextmanager
def trace_range(name: str):
    """Scoped profiler range ``raft_tpu.<name>`` (ref: nvtx.hpp range).

    Yields the open :class:`raft_tpu.obs.Span` (or ``None`` when obs is
    disabled) so call sites can attach stage timings::

        with trace_range("serve.batch") as sp:
            ...
            if sp is not None:
                sp.add_stage("dispatch", dt)
    """
    full = f"{DOMAIN}.{name}"
    with jax.profiler.TraceAnnotation(full), jax.named_scope(name):
        with _obs_spans().span(name) as sp:
            yield sp


def traced(name: Optional[str] = None) -> Callable[[F], F]:
    """Decorator form of :func:`trace_range` for public API entries.

    The wrapper carries ``__traced__`` (the range label) so static checks
    — ``tests/test_trace_coverage.py`` — can verify every public entry
    point ships observable.
    """

    def deco(fn: F) -> F:
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with trace_range(label):
                return fn(*args, **kwargs)

        wrapper.__traced__ = label  # type: ignore[attr-defined]
        return wrapper  # type: ignore[return-value]

    return deco


@contextlib.contextmanager
def profile(log_dir: str, *, host_tracer_level: int = 2):
    """Capture a profiler trace of the enclosed block into ``log_dir``.

    Thin wrapper over ``jax.profiler.trace`` so benches/tests don't import
    jax.profiler directly (mirrors the reference gating NVTX behind a CMake
    flag — here a no-op if RAFT_TPU_DISABLE_PROFILER is set).  The
    span-integrated variant lives at :func:`raft_tpu.obs.profile`.
    """
    if _env.env_bool("RAFT_TPU_DISABLE_PROFILER"):
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
