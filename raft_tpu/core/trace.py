"""Tracing / profiling ranges — nvtx parity for TPU.

The reference wraps every major entry point in an NVTX scoped range with a
dedicated ``raft`` domain (ref: cpp/include/raft/core/nvtx.hpp:49-82, used
at e.g. neighbors/detail/ivf_pq_build.cuh:1687).  The TPU equivalents are

- ``jax.profiler.TraceAnnotation`` — host-side Perfetto trace range, shows
  up in ``jax.profiler.trace`` captures (the "domain" maps to the
  ``raft_tpu.`` prefix);
- ``jax.named_scope`` — attaches the name to the HLO ops traced under the
  range so device-side work is attributable in the profile.

Both are near-zero-cost when no profiler session is active.
"""

from __future__ import annotations

import contextlib
import functools
import os
from typing import Callable, Optional, TypeVar

import jax

DOMAIN = "raft_tpu"

F = TypeVar("F", bound=Callable)


@contextlib.contextmanager
def trace_range(name: str):
    """Scoped profiler range ``raft_tpu.<name>`` (ref: nvtx.hpp range)."""
    full = f"{DOMAIN}.{name}"
    with jax.profiler.TraceAnnotation(full), jax.named_scope(name):
        yield


def traced(name: Optional[str] = None) -> Callable[[F], F]:
    """Decorator form of :func:`trace_range` for public API entries."""

    def deco(fn: F) -> F:
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with trace_range(label):
                return fn(*args, **kwargs)

        return wrapper  # type: ignore[return-value]

    return deco


@contextlib.contextmanager
def profile(log_dir: str, *, host_tracer_level: int = 2):
    """Capture a profiler trace of the enclosed block into ``log_dir``.

    Thin wrapper over ``jax.profiler.trace`` so benches/tests don't import
    jax.profiler directly (mirrors the reference gating NVTX behind a CMake
    flag — here a no-op if RAFT_TPU_DISABLE_PROFILER is set).
    """
    if os.environ.get("RAFT_TPU_DISABLE_PROFILER"):
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
