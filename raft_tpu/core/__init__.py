"""Core runtime: resources/context, serialization, logging, bitset.

TPU-native re-expression of the reference's core layer
(ref: cpp/include/raft/core/ — resources.hpp, serialize.hpp, logger, bitset.hpp).
"""

from raft_tpu.core.resources import (
    Resources,
    DeviceResources,
    default_resources,
    set_default_resources,
)
from raft_tpu.core.bitset import Bitset
from raft_tpu.core import serialize
from raft_tpu.core.validation import RaftError, LogicError, expects, fail
from raft_tpu.core.fanout import async_fanout, prefetch_to_device, row_batches

__all__ = [
    "Resources",
    "DeviceResources",
    "default_resources",
    "set_default_resources",
    "Bitset",
    "serialize",
    "RaftError",
    "LogicError",
    "expects",
    "fail",
    "async_fanout",
    "prefetch_to_device",
    "row_batches",
]
