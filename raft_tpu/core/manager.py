"""device_resources_manager — process-wide pooled per-device Resources.

Reference: ``core/device_resources_manager.hpp:34-577`` — a singleton that
hands multithreaded services a pooled ``device_resources`` per GPU with
configured stream pools and memory limits. TPU shape: one process drives
all local devices, so the pool maps device ordinal → a cached ``Resources``
bound to that device, with settable defaults applied before first use.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import jax

from raft_tpu.core.resources import Resources

_lock = threading.Lock()
_pool: Dict[int, Resources] = {}
_defaults = {"workspace_limit_bytes": 256 * 1024 * 1024, "seed": 0}
_frozen = False


def set_workspace_limit(limit_bytes: int) -> None:
    """Configure the workspace budget for future pooled handles
    (ref: device_resources_manager set_mem_pool/limit setters — like the
    reference, settings only apply before a device's handle is created)."""
    global _frozen
    with _lock:
        if _frozen:
            raise RuntimeError(
                "device_resources_manager settings are frozen after first use"
            )
        _defaults["workspace_limit_bytes"] = int(limit_bytes)


def set_seed(seed: int) -> None:
    global _frozen
    with _lock:
        if _frozen:
            raise RuntimeError(
                "device_resources_manager settings are frozen after first use"
            )
        _defaults["seed"] = int(seed)


def get_device_resources(device_id: int = 0) -> Resources:
    """Pooled Resources for one local device (ref:
    device_resources_manager::get_device_resources)."""
    global _frozen
    with _lock:
        if device_id not in _pool:
            devs = jax.local_devices()
            if not 0 <= device_id < len(devs):
                raise ValueError(
                    f"device_id {device_id} out of range ({len(devs)} local devices)"
                )
            _frozen = True  # only after validation: a bad id must not freeze
            _pool[device_id] = Resources(
                device=devs[device_id],
                seed=_defaults["seed"] + device_id,
                workspace_limit_bytes=_defaults["workspace_limit_bytes"],
            )
        return _pool[device_id]


def reset() -> None:
    """Drop pooled handles and unfreeze settings (tests)."""
    global _frozen
    with _lock:
        _pool.clear()
        _frozen = False
