"""Typed access to the ``RAFT_TPU_*`` environment knobs.

Every knob the package reads is declared once in :data:`KNOWN_VARS` —
name, type, default and one-line effect — and read through a typed
accessor (:func:`env_str` / :func:`env_int` / :func:`env_float` /
:func:`env_bool`).  The declaration table is the process-wide registry
the ENVREG static checker (``raft_tpu.analysis``) reconciles against
both the call sites and the README env table, so a knob cannot exist
without documentation and documentation cannot outlive the knob.

Reads stay point-of-use (no global config object is built from this
table); the accessors only add name/type validation and a single place
to define boolean semantics.  The few reads that must run before the
package imports (the jax platform/compile-cache bootstrap in
``raft_tpu/__init__.py`` and ``raft_tpu.bench.__main__``) keep direct
``os.environ`` access with an inline suppression — importing this
module there would drag ``raft_tpu.core`` (and jax) in too early.

This module is importable without jax: the analysis CLI and the tier-1
static tests load it standalone.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

__all__ = [
    "EnvVar",
    "KNOWN_VARS",
    "UnknownEnvVarError",
    "env_str",
    "env_int",
    "env_float",
    "env_bool",
    "has",
    "raw",
    "known",
]


@dataclass(frozen=True)
class EnvVar:
    """One declared knob: the registry row the checkers reconcile."""

    name: str
    kind: str        # "str" | "int" | "float" | "bool"
    default: str     # human-readable default, mirrors the README table
    help: str        # one-line effect


#: every environment variable the package (and its bench/test harnesses)
#: reads — the single source of truth the README table must mirror
KNOWN_VARS: Tuple[EnvVar, ...] = (
    # -- serving -------------------------------------------------------------
    EnvVar("RAFT_TPU_PIPELINE_DEPTH", "int", "2",
           "serving in-flight window: device batches the MicroBatcher "
           "overlaps; 1 = fully serial dispatch"),
    EnvVar("RAFT_TPU_COST_ACCOUNTING", "bool", "1",
           "0 skips the per-bucket XLA cost/memory gauges at warmup"),
    EnvVar("RAFT_TPU_SHARD_MERGE_DTYPE", "str", "float32",
           "bfloat16 quantizes the cross-shard merge all-gather of "
           "ShardedIndex candidate distances"),
    EnvVar("RAFT_TPU_SHARD_CAGRA", "str", "brute",
           "graph serves sharded CAGRA by partitioned graph traversal "
           "with halo frontiers; brute keeps the row-partitioned "
           "brute-refine control arm"),
    EnvVar("RAFT_TPU_SHARD_CAGRA_HALO", "int", "unset",
           "cap on replicated halo rows per shard of graph-mode sharded "
           "CAGRA (0 = no halo; unset keeps every cross-cut neighbor)"),
    EnvVar("RAFT_TPU_SHARD_CAGRA_SYNC_STEPS", "int", "4",
           "local traversal hops between cross-shard frontier exchanges "
           "in graph-mode sharded CAGRA (fixed cadence keeps the "
           "collective count static and recompile-free)"),
    EnvVar("RAFT_TPU_RAGGED", "bool", "unset",
           "1 serves SearchService indexes in ragged mode: per-request k "
           "and filter id packed as descriptor data into one executable "
           "per capacity bucket"),
    EnvVar("RAFT_TPU_RAGGED_KMAX", "int", "32",
           "ragged serving's static top-k capacity — every dispatch "
           "computes this many columns; per-request k may not exceed it"),
    EnvVar("RAFT_TPU_RAGGED_FILTERS", "bool", "1",
           "0 drops the per-request filter-id column from ragged "
           "dispatches (skips the RowFilter gather when no filters are "
           "registered)"),
    EnvVar("RAFT_TPU_OVERLOAD", "bool", "unset",
           "1 installs the overload actuators (admission control + "
           "degraded-mode search) on every SearchService index"),
    EnvVar("RAFT_TPU_OVERLOAD_ADMIT_WAIT_S", "float", "0.25",
           "oldest queued request wait that counts as pressure level 1 "
           "at batch cut (each doubling adds a level)"),
    EnvVar("RAFT_TPU_OVERLOAD_QUEUE_FACTOR", "float", "8.0",
           "queue depth in units of max_batch that counts as pressure "
           "level 1 (each doubling adds a level)"),
    EnvVar("RAFT_TPU_OVERLOAD_DEGRADE_AFTER_S", "float", "1.0",
           "sustained pressure before the degraded-search level steps "
           "up one notch"),
    EnvVar("RAFT_TPU_OVERLOAD_RESTORE_AFTER_S", "float", "5.0",
           "sustained calm before the degraded-search level steps back "
           "down one notch (hysteresis against flapping)"),
    EnvVar("RAFT_TPU_OVERLOAD_MAX_DEGRADE", "int", "2",
           "deepest degraded-search level (each level halves n_probes / "
           "itopk_size; every level's executables are warmed)"),
    EnvVar("RAFT_TPU_OVERLOAD_HEDGE", "bool", "unset",
           "1 hedges priority-0 dispatches across replica-group members "
           "(requires SearchService(replicas=...))"),
    EnvVar("RAFT_TPU_OVERLOAD_HEDGE_MULT", "float", "3.0",
           "hedge delay as a multiple of the live p99 latency"),
    EnvVar("RAFT_TPU_OVERLOAD_HEDGE_MIN_S", "float", "0.005",
           "hedge delay floor in seconds (used verbatim before the "
           "latency reservoir has data)"),
    # -- compaction ----------------------------------------------------------
    EnvVar("RAFT_TPU_COMPACT_DISABLED", "bool", "unset",
           "1 keeps the compaction worker down even when "
           "SearchService(compaction=True)"),
    EnvVar("RAFT_TPU_COMPACT_MAX_SIDE_ROWS", "int", "1024",
           "live side-buffer rows that trigger a compaction pass"),
    EnvVar("RAFT_TPU_COMPACT_MAX_TOMBSTONE_FRAC", "float", "0.25",
           "tombstoned fraction of main rows that triggers a pass"),
    EnvVar("RAFT_TPU_COMPACT_INTERVAL_S", "float", "2.0",
           "compaction worker scan period"),
    EnvVar("RAFT_TPU_COMPACT_COOLDOWN_S", "float", "30",
           "per-index re-arm delay after an aborted pass"),
    EnvVar("RAFT_TPU_COMPACT_HEADROOM_FRAC", "float", "4.0",
           "memory budget: projected peak rebuild bytes may not exceed "
           "this fraction of the live index's bytes"),
    EnvVar("RAFT_TPU_COMPACT_CHUNK_ROWS", "int", "65536",
           "main-structure decode chunk during the shadow gather"),
    EnvVar("RAFT_TPU_COMPACT_GATE_QUERIES", "int", "64",
           "held-back sample size for the recall gate"),
    EnvVar("RAFT_TPU_COMPACT_RECALL_SLACK", "float", "0.02",
           "gate tolerance: shadow recall may trail serving recall by at "
           "most this"),
    # -- paged storage -------------------------------------------------------
    EnvVar("RAFT_TPU_PAGED", "bool", "unset",
           "1 serves SearchService indexes from paged storage (host "
           "cold pages + budget-sized HBM hot pool); unpaged monolithic "
           "buffers stay the default"),
    EnvVar("RAFT_TPU_PAGE_ROWS", "int", "1024",
           "rows per storage page (multiple of 8; IVF list capacity "
           "repads to a page multiple)"),
    EnvVar("RAFT_TPU_PAGE_HBM_BUDGET_MB", "int", "unset",
           "hard HBM budget for paged hot pools (and the compactor's "
           "projected-bytes gate); unset sizes pools to hold every page"),
    EnvVar("RAFT_TPU_PAGE_PREFETCH_DEPTH", "int", "2",
           "bounded queue depth of the async page-prefetch worker (full "
           "queue drops the hint; prefetch is advisory)"),
    # -- distributed build ---------------------------------------------------
    EnvVar("RAFT_TPU_BUILD_REDUCE_DTYPE", "str", "float32",
           "bfloat16/int8 quantizes the per-iteration centroid/codebook "
           "psum of the sharded index build (EQuARX-style)"),
    EnvVar("RAFT_TPU_BUILD_KNN_BLOCK_ROWS", "int", "unset",
           "row-block size of the ring kNN exchange in the sharded "
           "CAGRA graph build (default: one shard's rows per step)"),
    # -- observability -------------------------------------------------------
    EnvVar("RAFT_TPU_OBS_DISABLED", "bool", "unset",
           "1 disables span recording entirely (metrics stay on)"),
    EnvVar("RAFT_TPU_SLOW_QUERY_MS", "float", "250",
           "slow-query log threshold (spans over it are recorded with "
           "their stage anatomy)"),
    EnvVar("RAFT_TPU_SPAN_RING", "int", "512",
           "capacity of the finished-span ring behind obs.recent_spans()"),
    EnvVar("RAFT_TPU_FLIGHT_CAP", "int", "256",
           "flight-recorder ring size (batch + event records kept for "
           "incident dumps)"),
    EnvVar("RAFT_TPU_FLIGHT_DIR", "str", "system temp",
           "where auto/manual flight dumps (JSON + Chrome trace) are "
           "written"),
    EnvVar("RAFT_TPU_FLIGHT_DEBOUNCE_S", "float", "60",
           "minimum seconds between auto-dumps; suppressed triggers are "
           "counted"),
    EnvVar("RAFT_TPU_EXPLAIN", "bool", "unset",
           "1 enables always-on explain tail sampling (the QueryArchive "
           "retains full plans for the interesting tail; deep explains "
           "work without it)"),
    EnvVar("RAFT_TPU_EXPLAIN_ARCHIVE_CAP", "int", "128",
           "query-archive ring size (archived ExplainPlans; oldest "
           "evicted first)"),
    EnvVar("RAFT_TPU_EXPLAIN_TAIL_PER_WINDOW", "int", "4",
           "slowest-N requests the explain tail sampler keeps per "
           "one-second window"),
    EnvVar("RAFT_TPU_EVENTS_RING", "int", "256",
           "obs event-bus recent-events ring capacity (overflow is "
           "counted, never blocking)"),
    EnvVar("RAFT_TPU_INCIDENT_WINDOW_S", "float", "5",
           "correlation window: trigger events this close join one "
           "incident (and share one flight dump)"),
    EnvVar("RAFT_TPU_INCIDENT_AUTOCLOSE_S", "float", "30",
           "quiet seconds after which an open incident auto-closes"),
    EnvVar("RAFT_TPU_INCIDENT_MAX_OPEN", "int", "8",
           "bound on simultaneously open incidents (excess triggers are "
           "counted, not tracked)"),
    EnvVar("RAFT_TPU_INCIDENT_DIR", "str", "flight dir",
           "where closed-incident JSON + Chrome-trace exports are "
           "written"),
    EnvVar("RAFT_TPU_SLO_WINDOW_SCALE", "float", "1.0",
           "scales every SLO window (eval period, burn windows, budget "
           "window) — tests shrink hours to milliseconds"),
    EnvVar("RAFT_TPU_SLO_EVAL_S", "float", "10",
           "SLO evaluator tick period (before window scaling)"),
    EnvVar("RAFT_TPU_SLO_BUDGET_WINDOW_S", "float", "2592000",
           "error-budget window (30 days, before window scaling)"),
    EnvVar("RAFT_TPU_SLO_AVAILABILITY", "float", "0.999",
           "default availability objective for watched indexes"),
    EnvVar("RAFT_TPU_SLO_P99_MS", "float", "250",
           "default latency-SLO target: requests over this are slow"),
    EnvVar("RAFT_TPU_SLO_RECALL", "float", "0.9",
           "default audited-recall objective for watched indexes"),
    EnvVar("RAFT_TPU_SLO_FRESHNESS_S", "float", "300",
           "default freshness target: max age of the oldest un-compacted "
           "mutation"),
    EnvVar("RAFT_TPU_AUTOTUNE", "bool", "unset",
           "1 runs the closed-loop SLO autotuner on every served index "
           "(SearchService(autotune=...) overrides)"),
    EnvVar("RAFT_TPU_AUTOTUNE_EVAL_S", "float", "2",
           "autotuner tick period (scaled by RAFT_TPU_SLO_WINDOW_SCALE)"),
    EnvVar("RAFT_TPU_AUTOTUNE_RECALL_FLOOR", "float", "0.9",
           "recall EWMA floor the autotuner must hold while trading "
           "effort for QPS"),
    EnvVar("RAFT_TPU_FRONTIER_PATH", "str", "unset",
           "serialized FrontierModel (bench frontier sweep output) the "
           "autotuner navigates; unset falls back to the synthetic "
           "effort-ladder model"),
    EnvVar("RAFT_TPU_GATEWAY", "bool", "unset",
           "1 gives every SearchService an operational HTTP gateway "
           "(scrape/probe/debug endpoints; SearchService(gateway=...) "
           "overrides)"),
    EnvVar("RAFT_TPU_GATEWAY_PORT", "int", "0",
           "gateway listen port (0 binds an ephemeral port, read back "
           "from OperationalGateway.port)"),
    EnvVar("RAFT_TPU_GATEWAY_TOKEN", "str", "unset",
           "bearer token the gateway's POST /admin plane requires; "
           "admin-on without a token refuses every admin request"),
    EnvVar("RAFT_TPU_GATEWAY_ADMIN", "bool", "unset",
           "1 enables the gateway's POST /admin plane (compact, "
           "effort_pin, flight_dump, archive_dump); off, those routes "
           "404"),
    EnvVar("RAFT_TPU_DISABLE_PROFILER", "bool", "unset",
           "1 disables the Perfetto capture helper"),
    EnvVar("RAFT_TPU_PERF_LEDGER", "bool", "1",
           "0 disables the measured perf ledger (per-executable "
           "device-time attribution + regression detection)"),
    EnvVar("RAFT_TPU_PERF_EWMA_ALPHA", "float", "0.25",
           "fast-EWMA weight of the per-bucket device-time regression "
           "detector (the slow baseline uses alpha/8)"),
    EnvVar("RAFT_TPU_PERF_REGRESSION_X", "float", "1.5",
           "regression trip ratio: fast device-time EWMA over this "
           "multiple of the slow baseline publishes perf_regression"),
    EnvVar("RAFT_TPU_PERF_MIN_SAMPLES", "int", "32",
           "dispatches per executable key before the regression "
           "detector arms (warm baselines only)"),
    EnvVar("RAFT_TPU_PERF_DEBOUNCE_S", "float", "60",
           "minimum seconds between perf_regression events (and profile "
           "captures) per executable key"),
    EnvVar("RAFT_TPU_PERF_CAPTURE_S", "float", "1.0",
           "duration of the auto profile capture a perf_regression "
           "triggers (0 disables the capture, the event still fires)"),
    EnvVar("RAFT_TPU_PERF_CAPTURE_DIR", "str", "flight dir",
           "where regression-triggered profiler captures are written"),
    EnvVar("RAFT_TPU_PEAK_FLOPS", "float", "per-platform",
           "roofline FLOP/s peak for obs.cost utilization estimates"),
    EnvVar("RAFT_TPU_PEAK_BW", "float", "per-platform",
           "roofline bytes/s peak for obs.cost utilization estimates"),
    # -- kernels / planners --------------------------------------------------
    EnvVar("RAFT_TPU_PALLAS", "str", "unset",
           "1 routes supported kernels through the Pallas "
           "implementations (kernels.use_pallas also accepts 0/auto)"),
    EnvVar("RAFT_TPU_PALLAS_SELECT_K", "bool", "1",
           "0 reverts the fused k-selection kernel to the XLA "
           "select paths (under the master RAFT_TPU_PALLAS gate)"),
    EnvVar("RAFT_TPU_PALLAS_CAGRA", "bool", "1",
           "0 reverts the fused CAGRA traversal hop to the XLA "
           "while-loop body (under the master RAFT_TPU_PALLAS gate)"),
    EnvVar("RAFT_TPU_HBM_BYTES", "int", "per-platform",
           "device memory budget the planners size against"),
    # -- process bootstrap ---------------------------------------------------
    EnvVar("RAFT_TPU_PLATFORM", "str", "auto",
           "force the jax platform for the raft_tpu.bench sweeps "
           "(cpu/tpu)"),
    EnvVar("RAFT_TPU_CACHE_DIR", "str", "~/.cache/raft_tpu/jax_cache",
           "persistent XLA compile cache location"),
    EnvVar("RAFT_TPU_NO_COMPILE_CACHE", "bool", "unset",
           "1 disables the persistent compile cache"),
    EnvVar("RAFT_TPU_COORDINATOR", "str", "unset",
           "multi-process jax distributed coordinator address"),
    EnvVar("RAFT_TPU_NUM_PROCS", "int", "unset",
           "multi-process jax distributed process count"),
    EnvVar("RAFT_TPU_PROC_ID", "int", "unset",
           "multi-process jax distributed process index"),
    # -- bench harness -------------------------------------------------------
    EnvVar("RAFT_TPU_BENCH_RECORD", "str", "BENCH_last.json",
           "bench record artifact path (- suppresses)"),
    EnvVar("RAFT_TPU_BENCH_PIPELINE_DEPTHS", "str", "1,2,4",
           "depth ladder for the bench.py serve pipeline A/B"),
    EnvVar("RAFT_TPU_BENCH_DEVICE_MS", "float", "10",
           "paced device interval for the serve A/B's async-device model"),
    EnvVar("RAFT_TPU_BENCH_SLO_ROUNDS", "int", "3",
           "interleaved off/on rounds pooled by the bench.py slo A/B"),
    EnvVar("RAFT_TPU_BENCH_N", "int", "500000",
           "accelerator bench corpus size"),
    EnvVar("RAFT_TPU_BENCH_DEADLINE_S", "float", "1500",
           "accelerator bench leg wall-clock budget"),
    EnvVar("RAFT_TPU_BENCH_CPU_DEADLINE_S", "float", "600",
           "CPU bench leg wall-clock budget"),
    # -- test harness --------------------------------------------------------
    EnvVar("RAFT_TPU_RUN_SLOW", "bool", "unset",
           "1 opts into @pytest.mark.slow tests (bench smokes, scale "
           "runs)"),
    EnvVar("RAFT_TPU_TEST_DEVICE", "bool", "unset",
           "1 enables the on-device test assertions"),
    EnvVar("RAFT_TPU_SCALE_N", "int", "test default",
           "corpus size override for the scale test suite"),
)

_KNOWN: Dict[str, EnvVar] = {v.name: v for v in KNOWN_VARS}

#: values env_bool reads as False when the variable IS set; anything
#: else set is True.  README rows say "1 enables" — but operators write
#: true/yes/on, and an explicit 0/false must mean off, not on.
_FALSY = frozenset({"", "0", "false", "no", "off"})


class UnknownEnvVarError(KeyError):
    """A read of a ``RAFT_TPU_*`` name missing from :data:`KNOWN_VARS`."""


def _declared(name: str, kind: str) -> EnvVar:
    var = _KNOWN.get(name)
    if var is None:
        raise UnknownEnvVarError(
            f"{name} is not declared in raft_tpu.core.env.KNOWN_VARS; "
            "add a row (and a README env-table entry) before reading it"
        )
    if var.kind != kind:
        raise TypeError(
            f"{name} is declared as {var.kind!r} but read as {kind!r}; "
            "fix the accessor or the KNOWN_VARS row"
        )
    return var


def known(name: str) -> bool:
    """Whether ``name`` is a declared knob (registry membership)."""
    return name in _KNOWN


def has(name: str) -> bool:
    """Whether the declared knob ``name`` is set in the environment."""
    if name not in _KNOWN:
        raise UnknownEnvVarError(
            f"{name} is not declared in raft_tpu.core.env.KNOWN_VARS"
        )
    return name in os.environ


def raw(name: str) -> Optional[str]:
    """The raw string value of a declared knob, ``None`` when unset.

    For save/restore around a scoped override (the bench A/B legs flip
    ``RAFT_TPU_PALLAS`` per case) where unset-vs-empty must round-trip.
    """
    if name not in _KNOWN:
        raise UnknownEnvVarError(
            f"{name} is not declared in raft_tpu.core.env.KNOWN_VARS"
        )
    return os.environ.get(name)


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    _declared(name, "str")
    return os.environ.get(name, default)


def env_int(name: str, default: Optional[int] = None) -> Optional[int]:
    _declared(name, "int")
    value = os.environ.get(name)
    if value is None or not value.strip():
        return default
    try:
        return int(value)
    except ValueError:
        raise ValueError(f"{name}={value!r} is not an integer") from None


def env_float(name: str, default: Optional[float] = None) -> Optional[float]:
    _declared(name, "float")
    value = os.environ.get(name)
    if value is None or not value.strip():
        return default
    try:
        return float(value)
    except ValueError:
        raise ValueError(f"{name}={value!r} is not a number") from None


def env_bool(name: str, default: bool = False) -> bool:
    _declared(name, "bool")
    value = os.environ.get(name)
    if value is None:
        return default
    return value.strip().lower() not in _FALSY
