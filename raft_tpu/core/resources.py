"""Resources / context object — the TPU-native analog of ``raft::resources``.

In the reference every public API takes ``raft::resources const&`` first; the
container is a lazily-populated, factory-keyed registry carrying the CUDA
stream, BLAS handles, workspace memory resource and communicator
(ref: cpp/include/raft/core/resources.hpp:49-138,
cpp/include/raft/core/resource/resource_types.hpp:29-50).

On TPU the analogs are: the JAX device (PJRT), an optional
``jax.sharding.Mesh`` for multi-chip execution, a deterministic PRNG key
stream (replacing per-handle cuRAND state), a workspace byte budget used by
tiled algorithms to pick tile sizes (replacing the RMM workspace resource),
and a comms handle (``raft_tpu.comms``) for collectives.

All raft_tpu public functions accept ``res: Resources | None`` as their first
argument; ``None`` means the process-wide default resources, so interactive
use stays ergonomic while services can inject isolated contexts
(ref: cpp/include/raft/core/device_resources.hpp:63-239 — ``device_resources``
is the same convenience pre-registration pattern).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np


class Resources:
    """Lazily-populated, factory-keyed resource registry.

    Mirrors ``raft::resources``'s add_resource_factory/get_resource contract
    (ref: cpp/include/raft/core/resources.hpp:93-132): resources are created
    on first access by a registered factory and cached. Shallow copies share
    the registry, like the reference's copyable handle.
    """

    def __init__(
        self,
        device: Optional[jax.Device] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        seed: int = 0,
        workspace_limit_bytes: int = 256 * 1024 * 1024,
    ):
        self._factories: Dict[str, Callable[["Resources"], Any]] = {}
        self._resources: Dict[str, Any] = {}
        # reentrant: factories receive `self` and may legitimately look up
        # other resources from inside get_resource
        self._lock = threading.RLock()
        self._device = device
        self._mesh = mesh
        self._seed = seed
        self._key_counter = 0
        self.workspace_limit_bytes = workspace_limit_bytes

    # -- registry (ref: core/resources.hpp add_resource_factory:93) --------
    def add_resource_factory(self, key: str, factory: Callable[["Resources"], Any]) -> None:
        with self._lock:
            self._factories[key] = factory
            self._resources.pop(key, None)

    def has_resource_factory(self, key: str) -> bool:
        return key in self._factories or key in self._resources

    def get_resource(self, key: str) -> Any:
        with self._lock:
            if key not in self._resources:
                if key not in self._factories:
                    raise KeyError(f"no resource or factory registered for {key!r}")
                self._resources[key] = self._factories[key](self)
            return self._resources[key]

    def set_resource(self, key: str, value: Any) -> None:
        with self._lock:
            self._resources[key] = value

    # -- device / mesh -----------------------------------------------------
    @property
    def device(self) -> jax.Device:
        if self._device is None:
            self._device = jax.devices()[0]
        return self._device

    @property
    def mesh(self) -> Optional[jax.sharding.Mesh]:
        return self._mesh

    def set_mesh(self, mesh: jax.sharding.Mesh) -> None:
        self._mesh = mesh

    # -- PRNG stream (replaces per-handle cuRAND generator state;
    #    ref: cpp/include/raft/random/rng_state.hpp:29-52) ------------------
    def prng_key(self) -> jax.Array:
        """Return a fresh, deterministic PRNG key (threefry).

        Keys form a counter-based stream seeded by the constructor seed, so a
        Resources object reproduces the same sequence across runs — the
        functional analog of the reference's stateful ``rng_state`` advancing
        its subsequence counter.
        """
        with self._lock:
            c = self._key_counter
            self._key_counter += 1
        return jax.random.fold_in(jax.random.PRNGKey(self._seed), c)

    def reseed(self, seed: int) -> None:
        with self._lock:
            self._seed = seed
            self._key_counter = 0

    # -- native backing (ref: raft::resources is the native container; here
    #    the C++ handle backs the Python one so the workspace arena and any
    #    future native state share one registry) ---------------------------
    @property
    def native(self):
        """The C++ ``resources`` handle backing this object (lazily built;
        None when no toolchain is available). Created with the same
        workspace byte limit this object budgets tiles against; the two
        arenas account independently, so native scratch is bounded by the
        same figure, not pooled with device workspace."""
        key = "native_resources"
        if not self.has_resource_factory(key):
            from raft_tpu.core import native as _native

            def _make(res_):
                if not _native.available():
                    return None
                return _native.NativeResources(res_.workspace_limit_bytes)

            self.add_resource_factory(key, _make)
        return self.get_resource(key)

    # -- comms (ref: core/resource/comms.hpp — COMMUNICATOR resource) ------
    @property
    def comms(self):
        return self.get_resource("comms")

    def set_comms(self, comms) -> None:
        """Inject a communicator (ref: comms/std_comms.hpp build_comms_* +
        set_comms pattern, SURVEY §3.5)."""
        self.set_resource("comms", comms)

    # -- synchronization (ref: resource::sync_stream) -----------------------
    def sync(self, *arrays) -> None:
        """Block until given arrays (or all dispatched work) are ready.

        The analog of ``resource::sync_stream`` — JAX dispatch is async like
        CUDA streams; call this where the reference synchronizes. Sync is a
        cancellation point: another thread can abort it via
        ``core.interruptible.cancel`` (ref: interruptible::synchronize,
        core/interruptible.hpp:73).
        """
        from raft_tpu.core import interruptible as _intr

        _intr.check()
        if arrays:
            jax.block_until_ready(arrays)
        else:
            # effectively a fence: tiny transfer round-trip on this device
            jax.block_until_ready(jax.device_put(np.zeros(()), self.device))
        _intr.check()

    # -- workspace sizing ---------------------------------------------------
    def workspace_rows(self, row_bytes: int, cap: int = 1 << 16) -> int:
        """How many rows of ``row_bytes`` fit in the workspace budget.

        Tiled algorithms (brute-force kNN, pairwise distance) use this the
        way the reference sizes batches against the RMM workspace resource
        (ref: neighbors/detail/ivf_pq_search.cuh:549 get_max_batch_size).
        """
        n = max(1, self.workspace_limit_bytes // max(1, row_bytes))
        return int(min(n, cap))


# ``device_resources`` convenience alias (ref: core/device_resources.hpp:63).
DeviceResources = Resources

_default: Optional[Resources] = None
_default_lock = threading.Lock()


def default_resources() -> Resources:
    """Process-wide default Resources (lazily created).

    Analog of ``device_resources_manager``'s pooled per-device handles
    (ref: cpp/include/raft/core/device_resources_manager.hpp:34-577), reduced
    to the JAX model where one process drives all local devices.
    """
    global _default
    with _default_lock:
        if _default is None:
            _default = Resources()
        return _default


def set_default_resources(res: Resources) -> None:
    global _default
    with _default_lock:
        _default = res


def ensure(res: Optional[Resources]) -> Resources:
    """Internal: resolve an optional resources argument."""
    return res if res is not None else default_resources()
