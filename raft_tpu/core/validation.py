"""Systematic argument validation — the RAFT_EXPECTS / raft::exception
analog (ref: cpp/include/raft/core/error.hpp — RAFT_EXPECTS, RAFT_FAIL,
raft::exception with collected backtrace).

The reference guards every public entry with ``RAFT_EXPECTS(cond, fmt, ...)``
raising ``raft::logic_error``. Here the same discipline is a set of small
helpers raising :class:`RaftError` subtypes, so callers can catch one
exception family across the whole library while tests can assert on the
specific subtype.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence


class RaftError(Exception):
    """Base of all raft_tpu validation/runtime errors (ref: core/error.hpp
    raft::exception)."""


class LogicError(RaftError, ValueError):
    """Precondition violation (ref: raft::logic_error via RAFT_EXPECTS)."""


def expects(condition: bool, message: str) -> None:
    """RAFT_EXPECTS: raise LogicError when ``condition`` is false."""
    if not condition:
        raise LogicError(message)


def fail(message: str) -> None:
    """RAFT_FAIL: unconditional logic error."""
    raise LogicError(message)


def check_matrix(
    x,
    name: str = "input",
    *,
    ndim: int = 2,
    min_rows: int = 0,
    dtypes: Optional[Iterable] = None,
) -> None:
    """Validate an array argument's rank / row count / dtype."""
    expects(
        hasattr(x, "ndim") and x.ndim == ndim,
        f"{name} must be a rank-{ndim} array, got "
        f"{getattr(x, 'shape', type(x).__name__)}",
    )
    if min_rows:
        expects(
            x.shape[0] >= min_rows,
            f"{name} needs at least {min_rows} rows, got {x.shape[0]}",
        )
    if dtypes is not None:
        names = {str(d) for d in dtypes}
        expects(
            str(x.dtype) in names,
            f"{name} dtype {x.dtype} not in supported set {sorted(names)}",
        )


def check_same_cols(x, y, xname: str = "x", yname: str = "y") -> None:
    expects(
        x.shape[-1] == y.shape[-1],
        f"{xname} and {yname} must share the feature dimension: "
        f"{x.shape} vs {y.shape}",
    )


def check_in(value, allowed: Sequence, name: str = "argument") -> None:
    expects(
        value in allowed,
        f"{name} must be one of {sorted(map(str, allowed))}, got {value!r}",
    )


def check_positive(value: int, name: str = "argument") -> None:
    expects(value > 0, f"{name} must be positive, got {value}")
