"""NumPy-format serialization of arrays + version-stamped index headers.

Mirrors the reference's mdspan serializer, which writes standard ``.npy``
headers so artifacts interoperate with numpy (ref:
cpp/include/raft/core/serialize.hpp:36-122,
cpp/include/raft/core/detail/mdspan_numpy_serializer.hpp), and the
version-stamp discipline of the index serializers (ref:
cpp/include/raft/neighbors/detail/cagra/cagra_serialize.cuh:35-62
``serialization_version``).

Device arrays are staged through the host (``jax.device_get``), exactly as
the reference stages device memory through a host buffer.
"""

from __future__ import annotations

import io
import struct
from typing import Any, BinaryIO, Dict

import jax
import numpy as np

MAGIC = b"RAFTTPU\x00"


def serialize_scalar(fh: BinaryIO, value) -> None:
    """Write a scalar with an 8-byte type tag + fixed-width payload."""
    if isinstance(value, (bool, np.bool_)):
        fh.write(b"b")
        fh.write(struct.pack("<q", int(value)))
    elif isinstance(value, (int, np.integer)):
        fh.write(b"i")
        fh.write(struct.pack("<q", int(value)))
    elif isinstance(value, (float, np.floating)):
        fh.write(b"f")
        fh.write(struct.pack("<d", float(value)))
    elif isinstance(value, str):
        data = value.encode()
        fh.write(b"s")
        fh.write(struct.pack("<q", len(data)))
        fh.write(data)
    else:
        raise TypeError(f"unsupported scalar type {type(value)}")


def deserialize_scalar(fh: BinaryIO):
    tag = fh.read(1)
    if tag == b"b":
        return bool(struct.unpack("<q", fh.read(8))[0])
    if tag == b"i":
        return int(struct.unpack("<q", fh.read(8))[0])
    if tag == b"f":
        return float(struct.unpack("<d", fh.read(8))[0])
    if tag == b"s":
        n = struct.unpack("<q", fh.read(8))[0]
        return fh.read(n).decode()
    raise ValueError(f"bad scalar tag {tag!r}")


def serialize_array(fh: BinaryIO, arr) -> None:
    """Write one array in standard .npy format (host-staged)."""
    np.save(fh, np.asarray(jax.device_get(arr)), allow_pickle=False)


def deserialize_array(fh: BinaryIO) -> np.ndarray:
    return np.load(fh, allow_pickle=False)


def write_header(fh: BinaryIO, kind: str, version: int) -> None:
    """Magic + index kind + serialization version stamp."""
    fh.write(MAGIC)
    serialize_scalar(fh, kind)
    serialize_scalar(fh, version)


def read_header(fh: BinaryIO, expected_kind: str, expected_version: int) -> int:
    magic = fh.read(len(MAGIC))
    if magic != MAGIC:
        raise ValueError("not a raft_tpu serialized file (bad magic)")
    kind = deserialize_scalar(fh)
    if kind != expected_kind:
        raise ValueError(f"expected serialized {expected_kind!r}, found {kind!r}")
    version = deserialize_scalar(fh)
    if version != expected_version:
        raise ValueError(
            f"serialization version mismatch for {kind!r}: "
            f"file={version} supported={expected_version}"
        )
    return version


def save_tree(path_or_fh, kind: str, version: int, scalars: Dict[str, Any], arrays: Dict[str, Any]) -> None:
    """Save an index as (header, named scalars, named arrays)."""

    def _write(fh):
        write_header(fh, kind, version)
        serialize_scalar(fh, len(scalars))
        for name in sorted(scalars):
            serialize_scalar(fh, name)
            serialize_scalar(fh, scalars[name])
        serialize_scalar(fh, len(arrays))
        for name in sorted(arrays):
            serialize_scalar(fh, name)
            serialize_array(fh, arrays[name])

    if isinstance(path_or_fh, (str, bytes)):
        with open(path_or_fh, "wb") as fh:
            _write(fh)
    else:
        _write(path_or_fh)


def load_tree(path_or_fh, kind: str, version: int):
    """Load (scalars, arrays) saved by save_tree."""

    def _read(fh):
        read_header(fh, kind, version)
        scalars = {}
        for _ in range(deserialize_scalar(fh)):
            name = deserialize_scalar(fh)
            scalars[name] = deserialize_scalar(fh)
        arrays = {}
        for _ in range(deserialize_scalar(fh)):
            name = deserialize_scalar(fh)
            arrays[name] = deserialize_array(fh)
        return scalars, arrays

    if isinstance(path_or_fh, (str, bytes)):
        with open(path_or_fh, "rb") as fh:
            return _read(fh)
    return _read(path_or_fh)
