"""Async batch fan-out — the stream-pool parallelism analog.

The reference overlaps independent work by fanning batches across a CUDA
stream pool (core/resource/cuda_stream_pool.hpp; brute-force kNN fan-out
neighbors/detail/knn_brute_force.cuh:451-485). XLA's execution model gives
the same overlap through *async dispatch*: every jitted call returns
immediately with futures, and the runtime pipelines consecutive executions
(compute of call i overlaps host work and transfers of call i+1). These
helpers make that idiom a first-class component: dispatch everything, block
once.

Why not one giant program? A single fused program is usually best on TPU —
use these when batches are genuinely independent units (different shapes,
incremental arrival, per-batch host post-processing) where the reference
would have used the stream pool.
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, List, Sequence, Tuple

import jax
import jax.numpy as jnp


def async_fanout(fn: Callable, arg_tuples: Sequence[Tuple]) -> List:
    """Dispatch ``fn(*args)`` for every tuple without blocking between
    calls, then block once on everything — all executions are in flight
    together, back-to-back on device (the stream-pool fan-out shape).
    """
    outs = [fn(*args) for args in arg_tuples]
    jax.block_until_ready(outs)
    return outs


def row_batches(x, batch_rows: int) -> Iterator:
    """Slice a [n, ...] array into row batches of at most ``batch_rows``."""
    n = x.shape[0]
    for s in range(0, n, batch_rows):
        yield x[s : min(s + batch_rows, n)]


def prefetch_to_device(chunks: Iterable, lookahead: int = 2) -> Iterator:
    """Double-buffered host→device pipeline: keep ``lookahead`` chunks'
    transfers in flight ahead of the consumer (the H2D/compute overlap the
    reference gets from pinned-memory async copies on a side stream).
    """
    import collections

    queue: collections.deque = collections.deque()
    it = iter(chunks)
    for chunk in it:
        queue.append(jax.device_put(chunk))
        if len(queue) > lookahead:
            yield queue.popleft()
    while queue:
        yield queue.popleft()
