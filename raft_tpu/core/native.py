"""ctypes binding to the native core (libraft_tpu_core.so).

The C ABI plays the reference's ``raft_runtime`` role (SURVEY §2.15): a
stable non-templated boundary between the native runtime (resources,
workspace arena, logger, npy serializer, interruptible — cpp/include/
raft_tpu/core/) and Python. The library auto-builds from cpp/ on first use
(make, ~1s, no dependencies); everything degrades gracefully when no
toolchain is present (``available()`` → False).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_LIB = None
_LOCK = threading.Lock()
_CPP_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "cpp")

_DTYPES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int8): 2,
    np.dtype(np.uint8): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.int64): 5,
    np.dtype(np.uint32): 6,
    np.dtype(np.float16): 7,
}
_DTYPES_INV = {v: k for k, v in _DTYPES.items()}

LOG_CALLBACK = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p)


def _build(force: bool = False) -> Optional[str]:
    import glob as _glob

    cpp = os.path.abspath(_CPP_DIR)
    so = os.path.join(cpp, "libraft_tpu_core.so")
    srcs = _glob.glob(os.path.join(cpp, "src", "*.cc"))
    if (
        not force
        and os.path.exists(so)
        and srcs
        and all(os.path.getmtime(so) >= os.path.getmtime(s) for s in srcs)
    ):
        return so
    try:
        if force:
            subprocess.run(
                ["make", "-C", cpp, "clean"], check=True,
                capture_output=True, timeout=60,
            )
        subprocess.run(
            ["make", "-C", cpp, "-j4"], check=True,
            capture_output=True, timeout=120,
        )
        return so if os.path.exists(so) else None
    except Exception:
        return None


def _load():
    global _LIB
    with _LOCK:
        if _LIB is not None:
            return _LIB
        so = _build()
        if so is None:
            _LIB = False
            return _LIB
        lib = ctypes.CDLL(so)
        # probe the NEWEST exported symbol: an old mapping that predates
        # any entry bound below must degrade, not AttributeError mid-_load
        if not hasattr(lib, "rt_eps_neighbors_host"):
            # stale prebuilt library from before the algorithm entry points
            # existed. Rebuild for the *next* process (re-CDLL'ing the same
            # path in this one would hit the loader's pathname cache and
            # return the old mapping) and degrade gracefully now.
            _build(force=True)
            _LIB = False
            return _LIB
        lib.rt_last_error.restype = ctypes.c_char_p
        lib.rt_resources_create.restype = ctypes.c_void_p
        lib.rt_resources_create.argtypes = [ctypes.c_size_t]
        lib.rt_resources_destroy.argtypes = [ctypes.c_void_p]
        lib.rt_resources_copy.restype = ctypes.c_void_p
        lib.rt_resources_copy.argtypes = [ctypes.c_void_p]
        lib.rt_workspace_alloc.restype = ctypes.c_void_p
        lib.rt_workspace_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
        lib.rt_workspace_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.rt_workspace_used.restype = ctypes.c_size_t
        lib.rt_workspace_used.argtypes = [ctypes.c_void_p]
        lib.rt_workspace_high_water.restype = ctypes.c_size_t
        lib.rt_workspace_high_water.argtypes = [ctypes.c_void_p]
        lib.rt_log_set_level.argtypes = [ctypes.c_int]
        lib.rt_log_get_level.restype = ctypes.c_int
        lib.rt_log.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.rt_log_set_callback.argtypes = [LOG_CALLBACK, ctypes.c_void_p]
        lib.rt_npy_write.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int,
        ]
        lib.rt_npy_read_info.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.c_int,
        ]
        lib.rt_npy_read.argtypes = [ctypes.c_char_p, ctypes.c_void_p, ctypes.c_size_t]
        lib.rt_interruptible_token.restype = ctypes.c_void_p
        lib.rt_interruptible_cancel.argtypes = [ctypes.c_void_p]
        lib.rt_interruptible_cancelled.restype = ctypes.c_int
        lib.rt_interruptible_cancelled.argtypes = [ctypes.c_void_p]
        lib.rt_interruptible_check.restype = ctypes.c_int
        lib.rt_interruptible_check.argtypes = [ctypes.c_void_p]
        # algorithm entry points (ref: raft_runtime/neighbors/*.hpp role)
        lib.rt_alg_last_error.restype = ctypes.c_char_p
        lib.rt_refine_host.restype = ctypes.c_int
        lib.rt_refine_host.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,  # dataset
            ctypes.c_void_p, ctypes.c_int64,                   # queries
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,   # candidates, k
            ctypes.c_int,                                      # metric
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,    # outs, threads
        ]
        lib.rt_pack_list_layout.restype = ctypes.c_int
        lib.rt_pack_list_layout.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.rt_knn_host.restype = ctypes.c_int
        lib.rt_knn_host.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,  # dataset
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,  # queries, k
            ctypes.c_int,                                     # metric
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,   # outs, threads
        ]
        lib.rt_select_k_host.restype = ctypes.c_int
        lib.rt_select_k_host.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ]
        lib.rt_pairwise_distance_host.restype = ctypes.c_int
        lib.rt_pairwise_distance_host.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,                  # x, m
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,  # y, n, d
            ctypes.c_int, ctypes.c_void_p, ctypes.c_int,      # metric, out, threads
        ]
        lib.rt_kmeans_fit_host.restype = ctypes.c_int
        lib.rt_kmeans_fit_host.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int,
        ]
        lib.rt_rmat_host.restype = ctypes.c_int
        lib.rt_rmat_host.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_uint64, ctypes.c_void_p, ctypes.c_void_p,
        ]
        # native hnswlib-format engine (ref: the hnswlib role of
        # cpp/bench/ann/src/hnswlib/hnswlib_wrapper.h)
        lib.rt_hnsw_last_error.restype = ctypes.c_char_p
        lib.rt_hnsw_load.restype = ctypes.c_int
        lib.rt_hnsw_load.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p),
        ]
        lib.rt_hnsw_info.restype = ctypes.c_int
        lib.rt_hnsw_info.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.rt_hnsw_element.restype = ctypes.c_int
        lib.rt_hnsw_element.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_void_p,
        ]
        lib.rt_hnsw_search.restype = ctypes.c_int
        lib.rt_hnsw_search.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.rt_hnsw_free.argtypes = [ctypes.c_void_p]
        # ANN-index C ABI (ref: raft_runtime/neighbors/*.hpp span)
        lib.rt_ann_last_error.restype = ctypes.c_char_p
        lib.rt_ann_index_destroy.argtypes = [ctypes.c_void_p]
        lib.rt_ann_index_info.restype = ctypes.c_int
        lib.rt_ann_index_info.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.rt_ivf_flat_build.restype = ctypes.c_void_p
        lib.rt_ivf_flat_build.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.rt_ivf_flat_search.restype = ctypes.c_int
        lib.rt_ivf_flat_search.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ]
        lib.rt_ivf_pq_build.restype = ctypes.c_void_p
        lib.rt_ivf_pq_build.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.rt_ivf_pq_search.restype = ctypes.c_int
        lib.rt_ivf_pq_search.argtypes = lib.rt_ivf_flat_search.argtypes
        lib.rt_cagra_build.restype = ctypes.c_void_p
        lib.rt_cagra_build.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int,
        ]
        lib.rt_cagra_search.restype = ctypes.c_int
        lib.rt_cagra_search.argtypes = lib.rt_ivf_flat_search.argtypes
        lib.rt_ann_serialize.restype = ctypes.c_int
        lib.rt_ann_serialize.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_ann_deserialize.restype = ctypes.c_void_p
        lib.rt_ann_deserialize.argtypes = [ctypes.c_char_p]
        lib.rt_eps_neighbors_host.restype = ctypes.c_int
        lib.rt_eps_neighbors_host.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_float,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ]
        _LIB = lib
        return _LIB


def available() -> bool:
    return _load() is not False


def _lib():
    lib = _load()
    if lib is False:
        raise RuntimeError("native core unavailable (no toolchain?)")
    return lib


def _check(code: int):
    if code != 0:
        raise RuntimeError(_lib().rt_last_error().decode())


class NativeResources:
    """Handle over the C++ resources container (ref: raft::resources)."""

    def __init__(self, workspace_limit_bytes: int = 256 * 1024 * 1024, _h=None):
        self._h = _h or _lib().rt_resources_create(workspace_limit_bytes)
        if not self._h:
            raise RuntimeError("resources creation failed")

    def copy(self) -> "NativeResources":
        return NativeResources(_h=_lib().rt_resources_copy(self._h))

    def workspace_alloc(self, bytes_: int) -> int:
        p = _lib().rt_workspace_alloc(self._h, bytes_)
        if not p:
            raise MemoryError(_lib().rt_last_error().decode())
        return p

    def workspace_free(self, ptr: int) -> None:
        _check(_lib().rt_workspace_free(self._h, ptr))

    @property
    def workspace_used(self) -> int:
        return _lib().rt_workspace_used(self._h)

    @property
    def workspace_high_water(self) -> int:
        return _lib().rt_workspace_high_water(self._h)

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and _LIB not in (None, False):
            _LIB.rt_resources_destroy(h)


def npy_write(path: str, arr: np.ndarray) -> None:
    """Write through the native .npy serializer (byte-compatible with
    np.save; ref: core/serialize.hpp serialize_mdspan)."""
    arr = np.ascontiguousarray(arr)
    dt = _DTYPES[arr.dtype]
    shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
    _check(
        _lib().rt_npy_write(
            path.encode(), arr.ctypes.data_as(ctypes.c_void_p),
            shape, arr.ndim, dt,
        )
    )


def npy_read(path: str) -> np.ndarray:
    shape = (ctypes.c_int64 * 16)()
    rank = ctypes.c_int()
    dt = ctypes.c_int()
    _check(_lib().rt_npy_read_info(path.encode(), shape, ctypes.byref(rank),
                                   ctypes.byref(dt), 16))
    sh = tuple(shape[i] for i in range(rank.value))
    out = np.empty(sh, _DTYPES_INV[dt.value])
    _check(_lib().rt_npy_read(path.encode(), out.ctypes.data_as(ctypes.c_void_p),
                              out.nbytes))
    return out


def log_set_level(level: int) -> None:
    _lib().rt_log_set_level(level)


def log(level: int, msg: str) -> None:
    _lib().rt_log(level, msg.encode())


_cb_keepalive = []


def log_set_callback(fn) -> None:
    """fn(level: int, msg: str) — mirrors the reference's callback sink
    (core/detail/callback_sink.hpp) used for Python log integration."""
    if fn is None:
        _lib().rt_log_set_callback(LOG_CALLBACK(0), None)
        return
    cb = LOG_CALLBACK(lambda lvl, msg, _u: fn(lvl, msg.decode()))
    _cb_keepalive.append(cb)
    _lib().rt_log_set_callback(cb, None)


_METRIC_CODES = {"sqeuclidean": 0, "euclidean": 1, "inner_product": 2, "cosine": 3}


def refine_host(
    dataset: np.ndarray,
    queries: np.ndarray,
    candidates: np.ndarray,
    k: int,
    metric: str = "sqeuclidean",
    n_threads: int = 0,
):
    """Native exact candidate re-rank, threaded over queries
    (ref: neighbors/detail/refine_host-inl.hpp via the raft_runtime-style
    C ABI). Returns (distances [q, k] f32, indices [q, k] i32)."""
    if metric not in _METRIC_CODES:
        raise ValueError(f"unsupported native refine metric {metric!r}")
    dataset = np.ascontiguousarray(dataset, np.float32)
    queries = np.ascontiguousarray(queries, np.float32)
    candidates = np.ascontiguousarray(candidates, np.int32)
    if dataset.ndim != 2 or queries.ndim != 2 or candidates.ndim != 2:
        raise ValueError("dataset, queries and candidates must be 2-D")
    if queries.shape[1] != dataset.shape[1]:
        raise ValueError(
            f"queries dim {queries.shape[1]} != dataset dim {dataset.shape[1]}"
        )
    if candidates.shape[0] != queries.shape[0]:
        raise ValueError(
            f"candidates rows {candidates.shape[0]} != query count {queries.shape[0]}"
        )
    n_q, k_cand = candidates.shape
    out_d = np.empty((n_q, k), np.float32)
    out_i = np.empty((n_q, k), np.int32)
    code = _lib().rt_refine_host(
        dataset.ctypes.data_as(ctypes.c_void_p), dataset.shape[0], dataset.shape[1],
        queries.ctypes.data_as(ctypes.c_void_p), n_q,
        candidates.ctypes.data_as(ctypes.c_void_p), k_cand, k,
        _METRIC_CODES[metric],
        out_d.ctypes.data_as(ctypes.c_void_p),
        out_i.ctypes.data_as(ctypes.c_void_p),
        n_threads,
    )
    if code != 0:
        raise RuntimeError(_lib().rt_alg_last_error().decode())
    return out_d, out_i


def knn_host(
    dataset: np.ndarray,
    queries: np.ndarray,
    k: int,
    metric: str = "sqeuclidean",
    n_threads: int = 0,
):
    """Native exact brute-force kNN, threaded over queries — the
    groundtruth-generation path (ref: raft-ann-bench generate_groundtruth;
    raft_runtime/neighbors/brute_force.hpp role). Returns
    (distances [q, k] f32, indices [q, k] i32)."""
    if metric not in _METRIC_CODES:
        raise ValueError(f"unsupported native knn metric {metric!r}")
    dataset = np.ascontiguousarray(dataset, np.float32)
    queries = np.ascontiguousarray(queries, np.float32)
    if dataset.ndim != 2 or queries.ndim != 2:
        raise ValueError("dataset and queries must be 2-D")
    if queries.shape[1] != dataset.shape[1]:
        raise ValueError(
            f"queries dim {queries.shape[1]} != dataset dim {dataset.shape[1]}"
        )
    n_q = queries.shape[0]
    out_d = np.empty((n_q, k), np.float32)
    out_i = np.empty((n_q, k), np.int32)
    code = _lib().rt_knn_host(
        dataset.ctypes.data_as(ctypes.c_void_p), dataset.shape[0], dataset.shape[1],
        queries.ctypes.data_as(ctypes.c_void_p), n_q, k,
        _METRIC_CODES[metric],
        out_d.ctypes.data_as(ctypes.c_void_p),
        out_i.ctypes.data_as(ctypes.c_void_p),
        n_threads,
    )
    if code != 0:
        raise RuntimeError(_lib().rt_alg_last_error().decode())
    return out_d, out_i


def select_k_host(
    scores: np.ndarray, k: int, select_min: bool = True, n_threads: int = 0
):
    """Native batched top-k over host rows (ref: raft_runtime/matrix/
    select_k.hpp role). Returns (values [rows, k] f32, indices i32)."""
    scores = np.ascontiguousarray(scores, np.float32)
    if scores.ndim != 2:
        raise ValueError("scores must be 2-D")
    rows, cols = scores.shape
    out_v = np.empty((rows, k), np.float32)
    out_i = np.empty((rows, k), np.int32)
    code = _lib().rt_select_k_host(
        scores.ctypes.data_as(ctypes.c_void_p), rows, cols, k,
        1 if select_min else 0,
        out_v.ctypes.data_as(ctypes.c_void_p),
        out_i.ctypes.data_as(ctypes.c_void_p),
        n_threads,
    )
    if code != 0:
        raise RuntimeError(_lib().rt_alg_last_error().decode())
    return out_v, out_i


def pack_list_layout(labels: np.ndarray, n_lists: int, max_cap: int):
    """Native IVF list layout: (slot [n] i32, list [n] i64,
    center_map [n_lists'] i64, cap) with oversized lists split into shards
    (ref: the list layout of ivf_flat_build.cuh:88-154 + codepacker role)."""
    labels = np.ascontiguousarray(labels, np.int64)
    n = labels.shape[0]
    max_out = n_lists + (n // max(max_cap, 1)) + 1
    slot = np.empty(n, np.int32)
    lst = np.empty(n, np.int64)
    cmap = np.empty(max_out, np.int64)
    n_out = ctypes.c_int64()
    cap = ctypes.c_int64()
    code = _lib().rt_pack_list_layout(
        labels.ctypes.data_as(ctypes.c_void_p), n, n_lists, max_cap,
        slot.ctypes.data_as(ctypes.c_void_p),
        lst.ctypes.data_as(ctypes.c_void_p),
        cmap.ctypes.data_as(ctypes.c_void_p), max_out,
        ctypes.byref(n_out), ctypes.byref(cap),
    )
    if code != 0:
        raise RuntimeError(_lib().rt_alg_last_error().decode())
    return slot, lst, cmap[: n_out.value].copy(), int(cap.value)


def pairwise_distance_host(
    x: np.ndarray, y: np.ndarray, metric: str = "sqeuclidean",
    n_threads: int = 0,
) -> np.ndarray:
    """Native host pairwise distance matrix (ref: raft_runtime/distance/
    pairwise_distance.hpp role). Returns [m, n] f32."""
    if metric not in _METRIC_CODES:
        raise ValueError(f"unsupported native metric {metric!r}")
    x = np.ascontiguousarray(x, np.float32)
    y = np.ascontiguousarray(y, np.float32)
    if x.ndim != 2 or y.ndim != 2 or x.shape[1] != y.shape[1]:
        raise ValueError("x and y must be 2-D with equal dims")
    out = np.empty((x.shape[0], y.shape[0]), np.float32)
    code = _lib().rt_pairwise_distance_host(
        x.ctypes.data_as(ctypes.c_void_p), x.shape[0],
        y.ctypes.data_as(ctypes.c_void_p), y.shape[0], x.shape[1],
        _METRIC_CODES[metric], out.ctypes.data_as(ctypes.c_void_p), n_threads,
    )
    if code != 0:
        raise RuntimeError(_lib().rt_alg_last_error().decode())
    return out


def kmeans_fit_host(
    x: np.ndarray, init_centers: np.ndarray, n_iters: int = 20,
    n_threads: int = 0,
):
    """Native Lloyd iterations from given init centers (ref:
    raft_runtime/cluster/kmeans.hpp fit/cluster_cost/compute_new_centroids
    role). Returns (centers [k, d] f32, labels [n] i32, inertia float)."""
    x = np.ascontiguousarray(x, np.float32)
    centers = np.array(init_centers, np.float32, copy=True, order="C")
    if x.ndim != 2 or centers.ndim != 2 or x.shape[1] != centers.shape[1]:
        raise ValueError("x and init_centers must be 2-D with equal dims")
    labels = np.empty(x.shape[0], np.int32)
    inertia = ctypes.c_float()
    code = _lib().rt_kmeans_fit_host(
        x.ctypes.data_as(ctypes.c_void_p), x.shape[0], x.shape[1],
        centers.shape[0], int(n_iters),
        centers.ctypes.data_as(ctypes.c_void_p),
        labels.ctypes.data_as(ctypes.c_void_p),
        ctypes.byref(inertia), n_threads,
    )
    if code != 0:
        raise RuntimeError(_lib().rt_alg_last_error().decode())
    return centers, labels, float(inertia.value)


def rmat_host(
    r_scale: int, c_scale: int, n_edges: int,
    theta=(0.57, 0.19, 0.19), seed: int = 0,
):
    """Native R-MAT rectangular edge generator (ref: raft_runtime/random/
    rmat_rectangular_generator.hpp role; distribution parity, not bitwise).
    Returns (rows [n_edges] i64, cols [n_edges] i64)."""
    rows = np.empty(n_edges, np.int64)
    cols = np.empty(n_edges, np.int64)
    a, b, c = (float(t) for t in theta)
    code = _lib().rt_rmat_host(
        int(r_scale), int(c_scale), int(n_edges),
        a, b, c, int(seed) or 0,
        rows.ctypes.data_as(ctypes.c_void_p),
        cols.ctypes.data_as(ctypes.c_void_p),
    )
    if code != 0:
        raise RuntimeError(_lib().rt_alg_last_error().decode())
    return rows, cols


class HnswNativeIndex:
    """Native hnswlib-format index: independent C++ parser + true
    hierarchical HNSW search (ref: the hnswlib dependency's role in
    neighbors/hnsw.hpp and cpp/bench/ann/src/hnswlib/hnswlib_wrapper.h).

    Shares no code with the Python writer/parser in
    ``raft_tpu/neighbors/hnsw.py`` — loading a file written there through
    this class is a cross-language validation of the binary format.
    """

    def __init__(self, path: str, dim: int):
        self._h = None
        h = ctypes.c_void_p()
        code = _lib().rt_hnsw_load(
            os.fsencode(path), int(dim), ctypes.byref(h)
        )
        if code != 0:
            raise RuntimeError(_lib().rt_hnsw_last_error().decode())
        self._h = h
        self.dim = int(dim)

    @property
    def info(self) -> dict:
        n = ctypes.c_int64()
        dim = ctypes.c_int64()
        max_m0 = ctypes.c_int64()
        max_level = ctypes.c_int32()
        entry = ctypes.c_int32()
        code = _lib().rt_hnsw_info(
            self._h, ctypes.byref(n), ctypes.byref(dim), ctypes.byref(max_m0),
            ctypes.byref(max_level), ctypes.byref(entry),
        )
        if code != 0:
            raise RuntimeError(_lib().rt_hnsw_last_error().decode())
        return {
            "n": n.value, "dim": dim.value, "max_m0": max_m0.value,
            "max_level": max_level.value, "entrypoint": entry.value,
        }

    def element(self, i: int):
        """(vector [dim] f32, label int, level-0 links [max_m0] i32,
        -1 padded) — the cross-check surface for other parsers."""
        inf = self.info
        vec = np.empty(inf["dim"], np.float32)
        links = np.empty(inf["max_m0"], np.int32)
        label = ctypes.c_int64()
        code = _lib().rt_hnsw_element(
            self._h, int(i), vec.ctypes.data_as(ctypes.c_void_p),
            ctypes.byref(label), links.ctypes.data_as(ctypes.c_void_p),
        )
        if code != 0:
            raise RuntimeError(_lib().rt_hnsw_last_error().decode())
        return vec, int(label.value), links

    def search(
        self, queries: np.ndarray, k: int, ef: int = 64,
        metric: str = "sqeuclidean", n_seeds: int = 1, n_threads: int = 0,
    ):
        """hnswlib-semantics knn_query: greedy upper-level descent then
        ef-bounded best-first at layer 0. ``n_seeds > 1`` adds evenly-
        strided extra layer-0 starts — the escape hatch for directed
        CAGRA graphs / MIP spaces where a single-entry search routes
        poorly (stock hnswlib has no analog; default 1 keeps its exact
        semantics). Returns (distances [q, k] f32, labels [q, k] i64)."""
        if metric not in _METRIC_CODES:
            raise ValueError(f"unsupported hnsw metric {metric!r}")
        queries = np.ascontiguousarray(queries, np.float32)
        if queries.ndim != 2 or queries.shape[1] != self.dim:
            raise ValueError(f"queries must be [q, {self.dim}]")
        n_q = queries.shape[0]
        out_d = np.empty((n_q, k), np.float32)
        out_i = np.empty((n_q, k), np.int64)
        code = _lib().rt_hnsw_search(
            self._h, queries.ctypes.data_as(ctypes.c_void_p), n_q, int(k),
            int(ef), int(n_seeds), _METRIC_CODES[metric],
            out_d.ctypes.data_as(ctypes.c_void_p),
            out_i.ctypes.data_as(ctypes.c_void_p), n_threads,
        )
        if code != 0:
            raise RuntimeError(_lib().rt_hnsw_last_error().decode())
        return out_d, out_i

    def __del__(self):
        if getattr(self, "_h", None):
            try:
                _lib().rt_hnsw_free(self._h)
            except Exception:
                pass


class InterruptibleToken:
    """(ref: core/interruptible.hpp; pylibraft common/interruptible.pyx)"""

    def __init__(self):
        self._tok = _lib().rt_interruptible_token()

    def cancel(self) -> None:
        _lib().rt_interruptible_cancel(self._tok)

    @property
    def cancelled(self) -> bool:
        return bool(_lib().rt_interruptible_cancelled(self._tok))

    def check(self) -> None:
        code = _lib().rt_interruptible_check(self._tok)
        if code != 0:
            raise InterruptedError(_lib().rt_last_error().decode())


class NativeAnnIndex:
    """Host ANN index over the stable C ABI (ref: the consumer side of
    raft_runtime/neighbors/{ivf_flat,ivf_pq,cagra}.hpp).  Build with the
    ``ivf_flat``/``ivf_pq``/``cagra`` classmethods or :meth:`load`; search
    returns (distances, ids) numpy arrays.  The native engines are the
    non-Python half of the ABI — the TPU path stays the JAX package —
    and double as cross-language semantic checks of the JAX indexes."""

    _KINDS = {0: "ivf_flat", 1: "ivf_pq", 2: "cagra"}

    def __init__(self, handle):
        if not handle:
            raise RuntimeError(_lib().rt_ann_last_error().decode())
        self._h = handle

    # -- constructors ------------------------------------------------------
    @staticmethod
    def _metric_code(metric: str) -> int:
        if metric not in _METRIC_CODES:
            raise ValueError(f"unsupported native ANN metric {metric!r}")
        return _METRIC_CODES[metric]

    @classmethod
    def ivf_flat(cls, dataset: np.ndarray, n_lists: int,
                 metric: str = "sqeuclidean", *, kmeans_iters: int = 10,
                 n_threads: int = 0) -> "NativeAnnIndex":
        x = np.ascontiguousarray(dataset, np.float32)
        return cls(_lib().rt_ivf_flat_build(
            x.ctypes.data_as(ctypes.c_void_p), x.shape[0], x.shape[1],
            n_lists, cls._metric_code(metric), kmeans_iters, n_threads))

    @classmethod
    def ivf_pq(cls, dataset: np.ndarray, n_lists: int, pq_dim: int,
               metric: str = "sqeuclidean", *, kmeans_iters: int = 10,
               n_threads: int = 0) -> "NativeAnnIndex":
        x = np.ascontiguousarray(dataset, np.float32)
        return cls(_lib().rt_ivf_pq_build(
            x.ctypes.data_as(ctypes.c_void_p), x.shape[0], x.shape[1],
            n_lists, pq_dim, cls._metric_code(metric), kmeans_iters, n_threads))

    @classmethod
    def cagra(cls, dataset: np.ndarray, graph_degree: int = 32,
              metric: str = "sqeuclidean", *,
              n_threads: int = 0) -> "NativeAnnIndex":
        x = np.ascontiguousarray(dataset, np.float32)
        return cls(_lib().rt_cagra_build(
            x.ctypes.data_as(ctypes.c_void_p), x.shape[0], x.shape[1],
            graph_degree, cls._metric_code(metric), n_threads))

    @classmethod
    def load(cls, path: str) -> "NativeAnnIndex":
        return cls(_lib().rt_ann_deserialize(path.encode()))

    # -- introspection -----------------------------------------------------
    @property
    def info(self) -> dict:
        kind = ctypes.c_int64()
        n = ctypes.c_int64()
        d = ctypes.c_int64()
        extra = ctypes.c_int64()
        _lib().rt_ann_index_info(self._h, ctypes.byref(kind), ctypes.byref(n),
                                 ctypes.byref(d), ctypes.byref(extra))
        out = {"kind": self._KINDS.get(kind.value, kind.value),
               "size": n.value, "dim": d.value}
        out["graph_degree" if kind.value == 2 else "n_lists"] = extra.value
        return out

    # -- search / persist --------------------------------------------------
    def search(self, queries: np.ndarray, k: int, *, n_probes: int = 32,
               itopk: int = 64, n_threads: int = 0):
        """(dists [q, k] f32, ids [q, k] i32).  ``n_probes`` drives the IVF
        kinds, ``itopk`` the CAGRA beam."""
        q = np.ascontiguousarray(queries, np.float32)
        info = self.info
        if q.ndim != 2 or q.shape[1] != info["dim"]:
            raise ValueError(
                f"queries must be [n_q, {info['dim']}], got {q.shape}")
        n_q = q.shape[0]
        out_d = np.empty((n_q, k), np.float32)
        out_i = np.empty((n_q, k), np.int32)
        kind = info["kind"]
        fn = {"ivf_flat": _lib().rt_ivf_flat_search,
              "ivf_pq": _lib().rt_ivf_pq_search,
              "cagra": _lib().rt_cagra_search}[kind]
        knob = itopk if kind == "cagra" else n_probes
        code = fn(self._h, q.ctypes.data_as(ctypes.c_void_p), n_q, knob, k,
                  out_d.ctypes.data_as(ctypes.c_void_p),
                  out_i.ctypes.data_as(ctypes.c_void_p), n_threads)
        if code != 0:
            raise RuntimeError(_lib().rt_ann_last_error().decode())
        return out_d, out_i

    def save(self, path: str) -> None:
        code = _lib().rt_ann_serialize(self._h, path.encode())
        if code != 0:
            raise RuntimeError(_lib().rt_ann_last_error().decode())

    def __del__(self):
        if getattr(self, "_h", None):
            try:
                _lib().rt_ann_index_destroy(self._h)
            except Exception:
                pass


def eps_neighbors_host(dataset: np.ndarray, queries: np.ndarray,
                       eps: float, *, n_threads: int = 0):
    """Dense epsilon-neighborhood adjacency + degrees on the host C ABI
    (ref: raft_runtime/neighbors/eps_neighborhood.hpp).  ``eps`` is the
    L2 radius (squared internally, matching the reference's eps^2)."""
    x = np.ascontiguousarray(dataset, np.float32)
    q = np.ascontiguousarray(queries, np.float32)
    if x.ndim != 2 or q.ndim != 2 or q.shape[1] != x.shape[1]:
        raise ValueError(
            f"dataset/queries must be 2-D with equal dims, got "
            f"{x.shape} vs {q.shape}")
    n, n_q = x.shape[0], q.shape[0]
    adj = np.empty((n_q, n), np.uint8)
    vd = np.empty(n_q, np.int64)
    code = _lib().rt_eps_neighbors_host(
        x.ctypes.data_as(ctypes.c_void_p), n, x.shape[1],
        q.ctypes.data_as(ctypes.c_void_p), n_q,
        ctypes.c_float(eps * eps),
        adj.ctypes.data_as(ctypes.c_void_p),
        vd.ctypes.data_as(ctypes.c_void_p), n_threads)
    if code != 0:
        raise RuntimeError(_lib().rt_ann_last_error().decode())
    # C writes exactly 0/1 — reinterpret in place, no second dense copy
    return adj.view(bool), vd
