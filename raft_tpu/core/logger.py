"""Framework logging — RAFT_LOG_* parity.

Reference: ``core/logger-inl.hpp:72-110`` (spdlog singleton, runtime level,
callback sink) with ``RAFT_LOG_*`` macros used inside algorithms (e.g.
cagra's search_plan.cuh:119). Here: a standard ``logging`` logger named
``raft_tpu`` that algorithms emit structured debug lines through, plus a
bridge that forwards the native C++ core's log records into the same
logger so Python and C++ logs interleave in one stream
(ref: core/detail/callback_sink.hpp Python integration).
"""

from __future__ import annotations

import logging

logger = logging.getLogger("raft_tpu")


def child(name: str) -> logging.Logger:
    """Namespaced sub-logger (``raft_tpu.<name>``) — one configuration
    point (handlers/levels on ``raft_tpu``) fans out to every subsystem,
    the spdlog-singleton idiom of the reference.  Used by e.g. the
    slow-query log (``raft_tpu.obs.slowlog``) so its WARNING lines can be
    routed or silenced independently of algorithm debug output."""
    return logger.getChild(name)

# native levels (cpp/include/raft_tpu/core/logger.hpp) → logging levels
_NATIVE_TO_PY = {
    0: logging.CRITICAL,  # off → nothing should arrive, map high
    1: logging.CRITICAL,
    2: logging.ERROR,
    3: logging.WARNING,
    4: logging.INFO,
    5: logging.DEBUG,
    6: logging.DEBUG,  # trace
}

_bridged = False


def get_logger() -> logging.Logger:
    return logger


def bridge_native() -> bool:
    """Route the native core's log records into the ``raft_tpu`` logger.
    Returns False when no native toolchain is available. Idempotent."""
    global _bridged
    if _bridged:
        return True
    from raft_tpu.core import native

    if not native.available():
        return False
    native.log_set_callback(
        lambda lvl, msg: logger.log(_NATIVE_TO_PY.get(lvl, logging.INFO), "%s", msg)
    )
    _bridged = True
    return True
