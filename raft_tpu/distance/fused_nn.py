"""Fused pairwise-distance + arg-min (1-NN) — the k-means inner loop.

Reference: ``fusedL2NN`` / ``fusedL2NNMinReduce`` compute, for each row of x,
the nearest row of y without materializing the [m,n] distance matrix
(ref: cpp/include/raft/distance/fused_l2_nn-inl.cuh:79-194,
fused_distance_nn.cuh, detail/fused_distance_nn/).

TPU design: the distance tile IS a matmul (expanded L2), so we compute
row-tiles of the distance matrix on the MXU and immediately reduce them to
(min, argmin) — XLA fuses the epilogue+reduction into the matmul consumer, so
only [tile_m, n] ever exists in registers/VMEM. Functionally identical to the
reference's fused kernel with the tile loop expressed as ``lax.map``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.resources import Resources, ensure
from raft_tpu.distance.pairwise import distance_matrix_tile
from raft_tpu.core.trace import traced


def _tile_rows_for(res: Resources, n: int, m: int) -> int:
    return min(max(res.workspace_rows(4 * n), 8), max(m, 1))


@functools.partial(jax.jit, static_argnames=("metric", "sqrt", "tile_rows"))
def _fused_nn_jit(x, y, metric: str, sqrt: bool, tile_rows: int):
    m, d = x.shape
    n_tiles = (m + tile_rows - 1) // tile_rows
    pad = n_tiles * tile_rows - m
    xp = jnp.pad(x, ((0, pad), (0, 0)))
    tiles = xp.reshape(n_tiles, tile_rows, d)

    dist_metric = "sqeuclidean" if metric in ("euclidean", "l2", "sqeuclidean") else metric

    def one_tile(t):
        dist = distance_matrix_tile(t, y, dist_metric)
        idx = jnp.argmin(dist, axis=1).astype(jnp.int32)
        val = jnp.take_along_axis(dist, idx[:, None], axis=1)[:, 0]
        return val, idx

    vals, idxs = lax.map(one_tile, tiles)
    vals = vals.reshape(-1)[:m]
    idxs = idxs.reshape(-1)[:m]
    if sqrt and dist_metric == "sqeuclidean":
        vals = jnp.sqrt(vals)
    return vals, idxs


@traced("fused_nn.fused_l2_nn")
def fused_l2_nn(
    x: jax.Array,
    y: jax.Array,
    *,
    sqrt: bool = False,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """(min_dist [m], argmin [m]) of L2 distance from each x row to y rows
    (ref: fused_l2_nn-inl.cuh:79 fusedL2NN)."""
    res = ensure(res)
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    return _fused_nn_jit(x, y, "sqeuclidean", sqrt, _tile_rows_for(res, y.shape[0], x.shape[0]))


@traced("fused_nn.fused_l2_nn_argmin")
def fused_l2_nn_argmin(
    x: jax.Array, y: jax.Array, *, res: Optional[Resources] = None
) -> jax.Array:
    """Arg-min only (Python ref: pylibraft.distance.fused_l2_nn_argmin)."""
    return fused_l2_nn(x, y, res=res)[1]


@traced("fused_nn.fused_distance_nn_argmin")
def fused_distance_nn_argmin(
    x: jax.Array,
    y: jax.Array,
    *,
    metric: str = "sqeuclidean",
    res: Optional[Resources] = None,
) -> jax.Array:
    """Fused NN arg-min for L2 or cosine
    (ref: distance/fused_distance_nn.cuh; Python ref:
    pylibraft.distance.fused_distance_nn_argmin)."""
    res = ensure(res)
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if metric in ("euclidean", "l2", "sqeuclidean"):
        return fused_l2_nn(x, y, res=res)[1]
    if metric != "cosine":
        raise ValueError("fused_distance_nn supports l2/sqeuclidean/cosine")
    return _fused_nn_jit(x, y, "cosine", False, _tile_rows_for(res, y.shape[0], x.shape[0]))[1]


@traced("fused_nn.masked_l2_nn_argmin")
def masked_l2_nn_argmin(
    x: jax.Array,
    y: jax.Array,
    adj: jax.Array,
    group_idxs: Optional[jax.Array] = None,
    *,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Masked fused L2 NN (ref: distance/masked_nn.cuh): rows of x may only
    match allowed columns of y.

    ``adj`` is either a dense [m, n] boolean mask, or (with ``group_idxs``
    [n_groups] end-offsets over y) the reference's [m, n_groups] bigraph
    adjacency which we expand to the dense mask.
    """
    res = ensure(res)
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    adj = jnp.asarray(adj)
    n = y.shape[0]
    m = x.shape[0]
    if group_idxs is not None:
        # column j belongs to group g iff prev_end <= j < end_g
        ends = jnp.asarray(group_idxs)
        cols = jnp.arange(n)
        group_of_col = jnp.sum(cols[None, :] >= ends[:, None], axis=0)  # [n]
        adj = adj[:, group_of_col]

    # row-tiled like the other fused paths, so [tile, n] is the live set
    tile_rows = _tile_rows_for(res, n, m)
    n_tiles = (m + tile_rows - 1) // tile_rows
    pad = n_tiles * tile_rows - m
    xt = jnp.pad(x, ((0, pad), (0, 0))).reshape(n_tiles, tile_rows, x.shape[1])
    at = jnp.pad(adj, ((0, pad), (0, 0))).reshape(n_tiles, tile_rows, n)

    def one_tile(args):
        xx, aa = args
        dist = distance_matrix_tile(xx, y, "sqeuclidean")
        dist = jnp.where(aa, dist, jnp.inf)
        idx = jnp.argmin(dist, axis=1).astype(jnp.int32)
        val = jnp.take_along_axis(dist, idx[:, None], axis=1)[:, 0]
        return val, idx

    vals, idxs = lax.map(one_tile, (xt, at))
    return vals.reshape(-1)[:m], idxs.reshape(-1)[:m]
