"""Pairwise distances, fused 1-NN, Gram kernels (ref: raft/distance/)."""

from raft_tpu.distance.pairwise import (
    DISTANCE_TYPES,
    pairwise_distance,
    distance_matrix_tile,
)
from raft_tpu.distance.fused_nn import (
    fused_l2_nn_argmin,
    fused_distance_nn_argmin,
    fused_l2_nn,
    masked_l2_nn_argmin,
)
from raft_tpu.distance.kernels import gram_matrix, KernelParams

__all__ = [
    "DISTANCE_TYPES",
    "pairwise_distance",
    "distance_matrix_tile",
    "fused_l2_nn_argmin",
    "fused_distance_nn_argmin",
    "fused_l2_nn",
    "masked_l2_nn_argmin",
    "gram_matrix",
    "KernelParams",
]
