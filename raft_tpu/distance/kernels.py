"""Gram-matrix (SVM-style) kernels (ref: cpp/include/raft/distance/kernels.cuh,
detail/kernels/ — linear / polynomial / tanh / RBF over dense AND CSR inputs).

All four are matmul + elementwise epilogue → pure MXU + fused VPU on TPU.
CSR inputs route the inner product through the feature-tiled sparse Gram
(bounded memory in the feature dimension; see sparse/distance.py), matching
the reference's CSR kernel specializations
(detail/kernels/gram_matrix.cuh evaluate(csr_matrix_view...)).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Union

import jax
import jax.numpy as jnp

from raft_tpu.distance.pairwise import distance_matrix_tile
from raft_tpu.core.resources import Resources, ensure
from raft_tpu.core.trace import traced


@dataclass
class KernelParams:
    """(ref: detail/kernels/kernel_matrices.cuh KernelParams)"""

    kernel: str = "linear"  # linear | polynomial | tanh | rbf
    degree: int = 3
    gamma: float = 1.0
    coef0: float = 0.0


def _is_csr(x) -> bool:
    return hasattr(x, "indptr") and hasattr(x, "indices")


def _epilogue(ip, params: KernelParams, d2=None):
    k = params.kernel
    if k == "linear":
        return ip
    if k == "polynomial":
        return (params.gamma * ip + params.coef0) ** params.degree
    if k == "tanh":
        return jnp.tanh(params.gamma * ip + params.coef0)
    if k == "rbf":
        return jnp.exp(-params.gamma * d2)
    raise ValueError(f"unknown kernel {k!r}")


@traced("kernels.gram_matrix")
def gram_matrix(
    x,
    y=None,
    params: Optional[KernelParams] = None,
    *,
    res: Optional[Resources] = None,
) -> jax.Array:
    """Kernel Gram matrix over dense arrays or CSR matrices
    (ref: distance/kernels.cuh GramMatrix::evaluate — dense & CSR overloads)."""
    params = params or KernelParams()
    if _is_csr(x):
        from raft_tpu.sparse.distance import _sparse_gram, row_norms_sq

        res = ensure(res)
        y = x if y is None else y
        if not _is_csr(y):
            raise ValueError("CSR gram requires both operands CSR")
        ip = _sparse_gram(x, y, res)
        if params.kernel == "rbf":
            n2x, n2y = row_norms_sq(x), row_norms_sq(y)
            d2 = jnp.maximum(n2x[:, None] + n2y[None, :] - 2.0 * ip, 0.0)
            return _epilogue(ip, params, d2)
        return _epilogue(ip, params)

    x = jnp.asarray(x, jnp.float32)
    y = x if y is None else jnp.asarray(y, jnp.float32)
    if params.kernel == "rbf":
        return _epilogue(None, params, distance_matrix_tile(x, y, "sqeuclidean"))
    return _epilogue(x @ y.T, params)
