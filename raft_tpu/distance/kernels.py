"""Gram-matrix (SVM-style) kernels (ref: cpp/include/raft/distance/kernels.cuh,
detail/kernels/ — linear / polynomial / tanh / RBF over dense inputs).

All four are matmul + elementwise epilogue → pure MXU + fused VPU on TPU.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.distance.pairwise import distance_matrix_tile
from raft_tpu.core.trace import traced


@dataclass
class KernelParams:
    """(ref: detail/kernels/kernel_matrices.cuh KernelParams)"""

    kernel: str = "linear"  # linear | polynomial | tanh | rbf
    degree: int = 3
    gamma: float = 1.0
    coef0: float = 0.0


@traced("kernels.gram_matrix")
def gram_matrix(
    x: jax.Array,
    y: Optional[jax.Array] = None,
    params: Optional[KernelParams] = None,
) -> jax.Array:
    params = params or KernelParams()
    x = jnp.asarray(x, jnp.float32)
    y = x if y is None else jnp.asarray(y, jnp.float32)
    k = params.kernel
    if k == "linear":
        return x @ y.T
    if k == "polynomial":
        return (params.gamma * (x @ y.T) + params.coef0) ** params.degree
    if k == "tanh":
        return jnp.tanh(params.gamma * (x @ y.T) + params.coef0)
    if k == "rbf":
        d2 = distance_matrix_tile(x, y, "sqeuclidean")
        return jnp.exp(-params.gamma * d2)
    raise ValueError(f"unknown kernel {k!r}")
