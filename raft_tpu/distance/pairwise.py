"""Pairwise distance matrix over 20 metrics (ref: cpp/include/raft/distance/).

The reference's DistanceType enum lists 20 metrics
(ref: distance/distance_types.hpp:23-67); dispatch goes through per-metric
``distance_ops`` functors into a tiled CUDA kernel with an SM80 cutlass path
(ref: distance/distance-inl.cuh, detail/pairwise_matrix/dispatch-inl.cuh).

TPU mapping (SURVEY §2.5): "expanded" metrics decompose into Gram terms —
``d(x,y) = f(‖x‖, ‖y‖, x·y)`` — so the whole matrix is one MXU matmul plus a
broadcast epilogue that XLA fuses. "Unexpanded" metrics (L1, Canberra, …)
need the elementwise |x_i−y_i| tile; we compute them in row-tiles sized to
the workspace budget via ``lax.map`` so the [m,n,d] broadcast never
materializes at full m.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core import validation
from raft_tpu.core.resources import Resources, ensure
from raft_tpu.core.trace import traced

# Metric name → canonical key. Mirrors pylibraft's accepted names
# (ref: python/pylibraft/pylibraft/distance/pairwise_distance.pyx DISTANCE_TYPES).
DISTANCE_TYPES = {
    "euclidean": "euclidean",
    "l2": "euclidean",
    "sqeuclidean": "sqeuclidean",
    "cosine": "cosine",
    "inner_product": "inner_product",
    "l1": "l1",
    "cityblock": "l1",
    "manhattan": "l1",
    "taxicab": "l1",
    "chebyshev": "chebyshev",
    "linf": "chebyshev",
    "canberra": "canberra",
    "minkowski": "minkowski",
    "lp": "minkowski",
    "correlation": "correlation",
    "jaccard": "jaccard",
    "hellinger": "hellinger",
    "braycurtis": "braycurtis",
    "jensenshannon": "jensenshannon",
    "hamming": "hamming",
    "kl_divergence": "kl_divergence",
    "russellrao": "russellrao",
    "dice": "dice",
    "haversine": "haversine",
}

_EXPANDED = {
    "euclidean",
    "sqeuclidean",
    "cosine",
    "inner_product",
    "correlation",
    "jaccard",
    "hellinger",
    "russellrao",
    "dice",
}


# On TPU the MXU's default f32 matmul precision is bf16-accumulate; distances
# feed exact-recall gates, so force full f32 (3-pass bf16) for Gram terms.
_PREC = lax.Precision.HIGHEST


def _mm(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.matmul(a, b, precision=_PREC)


# 8-bit only: an int32 accumulator holds 255²·d exactly up to d≈33k; wider
# integer inputs would silently overflow it, so they take the f32 path.
_INT8_DTYPES = (jnp.int8, jnp.uint8)


def _int_gram(xt: jax.Array, y: jax.Array) -> Optional[jax.Array]:
    """Exact integer Gram x·yᵀ on the MXU's native int8 path when both
    operands are 8-bit (ref: the reference's int8/uint8 dataset templates,
    neighbors/detail/ivf_pq_build.cuh:1690 — on TPU int8 matmul is a
    first-class MXU mode, so low-precision data skips the f32 copy
    entirely)."""
    if (
        xt.dtype in _INT8_DTYPES
        and y.dtype in _INT8_DTYPES
        and xt.shape[1] <= 32_000
    ):
        return lax.dot_general(
            xt.astype(jnp.int32) if xt.dtype == jnp.uint8 else xt,
            (y.astype(jnp.int32) if y.dtype == jnp.uint8 else y).T,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        ).astype(jnp.float32)
    return None


def _expanded_tile(xt: jax.Array, y: jax.Array, metric: str) -> jax.Array:
    """Gram-term metrics: one matmul + fused epilogue.

    (ref: the ‖x‖²+‖y‖²−2x·y decomposition in
    distance/detail/distance_ops/l2_exp.cuh and cosine.cuh.)
    """
    if metric in ("euclidean", "sqeuclidean", "inner_product", "cosine"):
        int_ip = _int_gram(xt, y)
        if int_ip is not None:
            if metric == "inner_product":
                return int_ip
            xx = jnp.sum(
                xt.astype(jnp.float32) * xt.astype(jnp.float32), axis=1
            )
            yy = jnp.sum(y.astype(jnp.float32) * y.astype(jnp.float32), axis=1)
            if metric == "cosine":
                nx = jnp.sqrt(xx)
                ny = jnp.sqrt(yy)
                return 1.0 - int_ip / jnp.maximum(nx[:, None] * ny[None, :], 1e-30)
            d2 = jnp.maximum(xx[:, None] + yy[None, :] - 2.0 * int_ip, 0.0)
            return jnp.sqrt(d2) if metric == "euclidean" else d2
    f32 = xt.astype(jnp.float32)
    yf = y.astype(jnp.float32)
    if metric == "hellinger":
        # d = sqrt(max(0, 1 − Σ√(x_i y_i)))  (ref: distance_ops/hellinger.cuh)
        ip = _mm(jnp.sqrt(jnp.maximum(f32, 0)), jnp.sqrt(jnp.maximum(yf, 0)).T)
        return jnp.sqrt(jnp.maximum(1.0 - ip, 0.0))

    ip = _mm(f32, yf.T)
    if metric == "inner_product":
        return ip
    if metric in ("euclidean", "sqeuclidean"):
        xx = jnp.sum(f32 * f32, axis=1)
        yy = jnp.sum(yf * yf, axis=1)
        d2 = jnp.maximum(xx[:, None] + yy[None, :] - 2.0 * ip, 0.0)
        return jnp.sqrt(d2) if metric == "euclidean" else d2
    if metric == "cosine":
        nx = jnp.sqrt(jnp.sum(f32 * f32, axis=1))
        ny = jnp.sqrt(jnp.sum(yf * yf, axis=1))
        return 1.0 - ip / jnp.maximum(nx[:, None] * ny[None, :], 1e-30)
    if metric == "correlation":
        d = f32.shape[1]
        mx = jnp.mean(f32, axis=1)
        my = jnp.mean(yf, axis=1)
        # centered inner product via expansion: Σ(x−mx)(y−my) = x·y − d·mx·my
        cip = ip - d * mx[:, None] * my[None, :]
        # clamp variances before the product: cancellation can leave tiny
        # negatives for (near-)constant rows, which would blow up the ratio
        vx = jnp.maximum(jnp.sum(f32 * f32, axis=1) - d * mx * mx, 0.0)
        vy = jnp.maximum(jnp.sum(yf * yf, axis=1) - d * my * my, 0.0)
        denom = jnp.sqrt(vx[:, None] * vy[None, :])
        return jnp.where(denom > 1e-12, 1.0 - cip / jnp.maximum(denom, 1e-12), 1.0)
    if metric == "jaccard":
        # binary-set semantics: 1 − |x∩y| / |x∪y|  (ref: distance_ops/jaccard... via
        # expanded dot products on {0,1} data)
        sx = jnp.sum(f32, axis=1)
        sy = jnp.sum(yf, axis=1)
        union = sx[:, None] + sy[None, :] - ip
        return jnp.where(union > 0, 1.0 - ip / jnp.maximum(union, 1e-30), 0.0)
    if metric == "dice":
        sx = jnp.sum(f32, axis=1)
        sy = jnp.sum(yf, axis=1)
        tot = sx[:, None] + sy[None, :]
        return jnp.where(tot > 0, 1.0 - 2.0 * ip / jnp.maximum(tot, 1e-30), 0.0)
    if metric == "russellrao":
        d = f32.shape[1]
        return (d - ip) / d
    raise ValueError(metric)


def _elementwise_tile(xt: jax.Array, y: jax.Array, metric: str, p: float) -> jax.Array:
    """Unexpanded metrics over the [bm, n, d] broadcast tile
    (ref: distance/detail/distance_ops/{l1,canberra,lp_unexp,...}.cuh)."""
    f32 = xt.astype(jnp.float32)[:, None, :]
    yf = y.astype(jnp.float32)[None, :, :]
    if metric == "l1":
        return jnp.sum(jnp.abs(f32 - yf), axis=-1)
    if metric == "chebyshev":
        return jnp.max(jnp.abs(f32 - yf), axis=-1)
    if metric == "canberra":
        num = jnp.abs(f32 - yf)
        den = jnp.abs(f32) + jnp.abs(yf)
        return jnp.sum(jnp.where(den > 0, num / jnp.maximum(den, 1e-30), 0.0), axis=-1)
    if metric == "minkowski":
        return jnp.sum(jnp.abs(f32 - yf) ** p, axis=-1) ** (1.0 / p)
    if metric == "braycurtis":
        num = jnp.sum(jnp.abs(f32 - yf), axis=-1)
        den = jnp.sum(jnp.abs(f32 + yf), axis=-1)
        return jnp.where(den > 0, num / jnp.maximum(den, 1e-30), 0.0)
    if metric == "jensenshannon":
        m = 0.5 * (f32 + yf)
        safe_log = lambda a, b: jnp.where(a > 0, a * jnp.log(jnp.maximum(a, 1e-30) / jnp.maximum(b, 1e-30)), 0.0)
        js = 0.5 * jnp.sum(safe_log(f32, m) + safe_log(yf, m), axis=-1)
        return jnp.sqrt(jnp.maximum(js, 0.0))
    if metric == "hamming":
        return jnp.mean((f32 != yf).astype(jnp.float32), axis=-1)
    if metric == "kl_divergence":
        return jnp.sum(
            jnp.where(f32 > 0, f32 * jnp.log(jnp.maximum(f32, 1e-30) / jnp.maximum(yf, 1e-30)), 0.0),
            axis=-1,
        )
    raise ValueError(metric)


def _haversine_tile(xt: jax.Array, y: jax.Array) -> jax.Array:
    """Great-circle distance over [lat, lon] radians
    (ref: distance/detail/distance_ops/haversine.cuh)."""
    lat1, lon1 = xt[:, 0][:, None], xt[:, 1][:, None]
    lat2, lon2 = y[:, 0][None, :], y[:, 1][None, :]
    sdlat = jnp.sin(0.5 * (lat2 - lat1))
    sdlon = jnp.sin(0.5 * (lon2 - lon1))
    a = sdlat * sdlat + jnp.cos(lat1) * jnp.cos(lat2) * sdlon * sdlon
    return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


def distance_matrix_tile(
    x_tile: jax.Array, y: jax.Array, metric: str, p: float = 2.0
) -> jax.Array:
    """Distance matrix for one row-tile of x against all of y.

    The building block shared by pairwise_distance, brute-force kNN and IVF
    search — the analog of the reference's pairwise-matrix tile kernel
    (ref: distance/detail/pairwise_matrix/kernel_sm60.cuh).
    """
    metric = DISTANCE_TYPES[metric]
    if metric == "haversine":
        return _haversine_tile(x_tile, y)
    if metric in _EXPANDED:
        return _expanded_tile(x_tile, y, metric)
    return _elementwise_tile(x_tile, y, metric, p)


def argmin_tile_rows(n_centers: int, res) -> int:
    """Row-tile size for a fused distance+argmin against ``n_centers``
    targets, bounded by the resources' workspace budget (the [tile, L] f32
    score tile is the only distance-matrix memory)."""
    return int(min(max(res.workspace_rows(4 * max(n_centers, 1)), 8), 1 << 16))


@functools.partial(jax.jit, static_argnames=("metric", "tile_rows"))
def tiled_argmin(x, centers, metric: str, tile_rows: int):
    """Row-tiled fused distance+argmin: labels [n] int32.

    The shared building block for kmeans predict/fit assignment (the
    fusedL2NNMinReduce role, ref distance/fused_l2_nn-inl.cuh): only a
    [tile_rows, L] score tile is ever materialized, and ``x`` is consumed
    through slices (no padded copy — a full [n, L] matrix is ~200 GB at
    DEEP-scale n × 50k lists). ``metric`` is "sqeuclidean" or
    "inner_product"; normalize beforehand for cosine.

    Related fused-argmin variants: distance/fused_nn.py returns
    (min_dist, argmin) via a padded row-tile scan, and
    kernels/fused_argmin.py is the Pallas candidate for the same role —
    this is the labels-only, slice-tailed variant the kmeans loops use.
    """

    def score_argmin(t):
        if metric == "inner_product":
            d = -jnp.matmul(t, centers.T, precision=lax.Precision.HIGHEST)
        else:
            d = distance_matrix_tile(t, centers, "sqeuclidean")
        return jnp.argmin(d, axis=1).astype(jnp.int32)

    n = x.shape[0]
    tile_rows = min(tile_rows, n)
    if n <= tile_rows:
        return score_argmin(x)
    n_full = (n // tile_rows) * tile_rows
    main = lax.map(
        score_argmin, x[:n_full].reshape(-1, tile_rows, x.shape[1])
    ).reshape(n_full)
    if n_full == n:
        return main
    # final partial tile: score the last tile_rows rows (a static slice —
    # cheaper than padding a copy of all of x) and keep the new suffix
    tail = score_argmin(x[n - tile_rows:])
    return jnp.concatenate([main, tail[tile_rows - (n - n_full):]])


@functools.partial(jax.jit, static_argnames=("metric", "tile_rows"))
def _pairwise_jit(x, y, metric: str, p: float, tile_rows: int):
    m = x.shape[0]
    n_tiles = (m + tile_rows - 1) // tile_rows
    pad = n_tiles * tile_rows - m
    xp = jnp.pad(x, ((0, pad), (0, 0)))
    tiles = xp.reshape(n_tiles, tile_rows, x.shape[1])
    out = lax.map(lambda t: distance_matrix_tile(t, y, metric, p), tiles)
    return out.reshape(n_tiles * tile_rows, y.shape[0])[:m]


@traced("pairwise.pairwise_distance")
def pairwise_distance(
    x: jax.Array,
    y: Optional[jax.Array] = None,
    *,
    metric: str = "euclidean",
    p: float = 2.0,
    res: Optional[Resources] = None,
) -> jax.Array:
    """Full [m, n] pairwise distance matrix (ref: distance/distance-inl.cuh
    ``pairwise_distance``; Python ref:
    pylibraft/distance/pairwise_distance.pyx).

    Row-tiled against the resources' workspace budget so the elementwise
    broadcast never exceeds memory.

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.distance import pairwise_distance
    >>> x = np.zeros((2, 4), np.float32)
    >>> y = np.ones((3, 4), np.float32)
    >>> d = pairwise_distance(x, y, metric="euclidean")
    >>> d.shape
    (2, 3)
    >>> bool(np.allclose(np.asarray(d), 2.0))  # ‖0−1‖₂ over 4 dims
    True
    """
    res = ensure(res)
    x_is_y = y is None or y is x
    x = jnp.asarray(x)
    y = x if y is None else jnp.asarray(y)
    validation.check_in(metric, DISTANCE_TYPES, "metric")
    validation.check_matrix(x, "x")
    validation.check_matrix(y, "y")
    validation.check_same_cols(x, y)
    canonical = DISTANCE_TYPES[metric]
    n, d = y.shape
    if canonical in _EXPANDED or canonical == "haversine":
        row_bytes = 4 * n  # epilogue tile only
    else:
        row_bytes = 4 * n * d  # [tile, n, d] broadcast
    tile_rows = min(max(res.workspace_rows(row_bytes), 8), max(x.shape[0], 1))
    out = _pairwise_jit(x, y, canonical, p, tile_rows)
    if x_is_y and canonical != "inner_product":
        # d(x, x) is exactly 0 for every true distance here, but the
        # expanded ‖x‖²−2x·y+‖y‖² form cancels catastrophically in f32
        # (the sklearn euclidean_distances X-is-Y rule)
        diag = jnp.arange(out.shape[0])
        out = out.at[diag, diag].set(0.0)
    return out
