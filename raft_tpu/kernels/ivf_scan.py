"""Pallas probe-major IVF scan: per-list MXU scoring + VMEM-resident top-k.

The probe-major schedule (neighbors/_common.run_probe_major) streams each
probed list's rows from HBM once per query bucket.  Its XLA formulation
still materializes the per-step score tensor ([bb, G, cap]) and runs a
sort-based select over it in HBM.  This kernel fuses the two: for each
bucket the list's decoded rows are DMA'd into VMEM via a *dynamic block
index* (scalar-prefetched ``bucket_list`` drives the BlockSpec index_map —
the Pallas answer to data-dependent gathers, SURVEY §7 hard part 2), the
[G, cap] score tile is computed on the MXU, and the per-query top-k is
extracted in VMEM (toolkit.fold_topk) — scores never reach HBM.

Role parity: the reference's per-list ``compute_similarity`` scan kernel
(cpp/include/raft/neighbors/detail/ivf_pq_compute_similarity-inl.cuh) with
its shmem LUT + warp select; here the "LUT" is the decoded scan cache and
the warp queue is the VMEM fold.

Used by the ivf_pq AND ivf_flat probe-major paths when
``RAFT_TPU_PALLAS=1`` (same gate as the fused kNN kernel).  Coverage
(round 4 widened to match the reference's compute_similarity surface):

- **Metrics**: L2 (sqeuclidean/euclidean), **inner product**, and
  **cosine** (ivf_flat's normalized leg, same rsqrt floors as its XLA
  schedule).
- **Storage**: f32/bf16 rows upcast in VMEM; ivf_pq's **int8 scan cache
  takes the fused quantized-query leg** (per-query symmetric
  quantization, int8×int8 MXU dot, scan_scale rescale — the memory-lean
  DEEP-100M mode).  Raw int8/uint8 ivf_flat datasets stay on the XLA
  schedule (no dequant scale).
- **Filters**: bitset sample filters ride as a *packed per-list word
  table* ([L, ceil(cap/32)] uint32, n/8 bytes total — built by
  ``pack_list_filter`` from the global bitset once per search call).
  Each bucket DMAs its list's words (a few dozen bytes) and expands them
  to a lane mask in VMEM — the global bitset itself never needs to fit
  VMEM, which is what kept this leg XLA-only in round 3.

The kernel is payload-agnostic: ivf_pq feeds decoded reconstructions +
their norms, ivf_flat feeds raw rows + row norms.  Validated in interpret
mode on CPU plus a TPU-gated compile test.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.kernels.toolkit import fold_topk, quantize_queries_i8
from raft_tpu.ops import cost as ops_cost
from raft_tpu.store.paged import PagedLists

_WORST = float("inf")


def pack_list_filter_table(list_index: jax.Array, table: jax.Array):
    """Pack a whole filter registry for the ragged descriptor leg:
    ``table`` [F, W_global] global-bitset rows → [F, L, ceil(cap/32)]
    per-list word tables (``pack_list_filter`` vmapped over the filter
    axis).  Each query's prefetched ``fid`` then selects its own [L, cap_w]
    plane inside the kernel, so a batch mixing F different predicates
    shares one executable."""
    return jax.vmap(lambda fw: pack_list_filter(list_index, fw))(table)


def pack_list_filter(list_index: jax.Array, filter_words: jax.Array):
    """Pack the bitset pass/fail of every (list, slot) into per-list
    uint32 words ([L, ceil(cap/32)]): bit j of word w covers slot
    32·w + j.  One XLA gather over the [L, cap] id table — n/8 bytes of
    output, so a DEEP-100M filter table is ~12 MB next to a ~10 GB scan
    cache.  Padding slots (id < 0) pack as fail."""
    L, cap = list_index.shape
    safe = jnp.clip(list_index, 0)
    word = filter_words[safe // 32]
    bit = (word >> (safe % 32).astype(jnp.uint32)) & 1
    ok = (bit == 1) & (list_index >= 0)                  # [L, cap] bool
    cap_w = -(-cap // 32)
    ok = jnp.pad(ok, ((0, 0), (0, cap_w * 32 - cap)))
    ok = ok.reshape(L, cap_w, 32).astype(jnp.uint32)
    shifts = jnp.arange(32, dtype=jnp.uint32)[None, None, :]
    return jnp.sum(ok << shifts, axis=2).astype(jnp.uint32)


def _score_against_list(dec, qg, q2, y2_row, ids_row, filt_row, scale,
                        *, metric: str, filtered: bool, scan_dtype: str):
    """Score a query block against one list's rows — the shared inner
    piece of both fused schedules. ``dec`` [cap, rot] (any storage dtype),
    ``qg`` [G, rot] f32, ``q2`` [G, 1] f32 (+inf marks padding queries),
    ``y2_row``/``ids_row`` [1, cap], ``filt_row`` [1, cap_w] uint32.
    Returns (scores [G, cap] with invalid slots at +inf, cand_i [G, cap])."""
    G = qg.shape[0]
    cap = dec.shape[0]
    if dec.dtype == jnp.int8:
        q_i8, sq = quantize_queries_i8(qg)               # [G, rot], [G, 1]
        ip_i32 = jax.lax.dot_general(
            q_i8, dec,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )                                                # [G, cap]
        ip = ip_i32.astype(jnp.float32) * (sq * scale)
    else:
        # MXU: [G, rot] × [cap, rot]ᵀ; stored rows upcast in VMEM only.
        # scan_dtype mirrors the caller's XLA schedule so the two legs
        # rank ties the same way: "highest"/"float32" = f32 compute,
        # "bfloat16" = the ivf_pq lut_dtype ladder's bf16 compute
        sd = jnp.bfloat16 if scan_dtype == "bfloat16" else jnp.float32
        # precision parity with the XLA legs, measured on-chip (round 4):
        # Mosaic's DEFAULT f32 dot is a single bf16 pass, while XLA's f32
        # DEFAULT keeps ~f32 fidelity — near-equal candidates then rank
        # differently between the legs (id agreement 0.955 on clustered
        # bf16-storage data).  "float32" pins HIGHEST to match XLA's
        # effective precision; "bfloat16" casts both operands to bf16
        # first, so DEFAULT is already bit-matched to the XLA bf16 dot.
        ip = jax.lax.dot_general(
            qg.astype(sd), dec.astype(sd),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=(
                jax.lax.Precision.DEFAULT if scan_dtype == "bfloat16"
                else jax.lax.Precision.HIGHEST
            ),
        )                                                # [G, cap]
    if metric == "inner_product":
        scores = -ip
    elif metric == "cosine":
        # same guards as the XLA leg (ivf_flat score_fn): rsqrt with the
        # floors keeps padding (+inf q2 → rsqrt→0) and zero rows finite
        qn_inv = jax.lax.rsqrt(jnp.maximum(q2, 1e-24))   # [G, 1]
        vn_inv = jax.lax.rsqrt(jnp.maximum(y2_row, 1e-24))  # [1, cap]
        scores = 1.0 - ip * qn_inv * vn_inv
    else:
        scores = y2_row - 2.0 * ip + q2                  # [G, cap]
    invalid = (ids_row < 0) | jnp.isinf(q2)              # [G, cap]
    if filtered:
        cap_w = filt_row.shape[1]
        # lane-oriented expansion: repeat each word across its 32 lanes
        # (broadcast + minormost reshape — the only reshape shape Mosaic
        # lowers cheaply), then shift by lane position % 32
        rep = jnp.broadcast_to(
            filt_row[:, :, None], (1, cap_w, 32)
        ).reshape(1, cap_w * 32)
        shifts = (
            jax.lax.broadcasted_iota(jnp.uint32, (1, cap_w * 32), 1)
            % jnp.uint32(32)
        )
        passing = ((rep >> shifts) & 1)[:, :cap] == 1    # [1, cap]
        invalid = invalid | ~passing
    scores = jnp.where(invalid, _WORST, scores)
    cand_i = jnp.broadcast_to(ids_row, (G, cap))
    return scores, cand_i


def _scan_kernel(bucket_list_ref, dec_ref, y2_ref, ids_ref, filt_ref, qg_ref,
                 q2_ref, scale_ref, vals_ref, out_ids_ref, *, kk: int,
                 metric: str, filtered: bool, scan_dtype: str):
    """One bucket: score its list's rows against its G queries, keep the
    per-query top-kk.  dec/y2/ids/filt blocks were selected by the
    prefetched bucket_list (dynamic index_map); qg/q2 are the bucket's
    pre-gathered rotated queries (+inf q2 marks padding slots).  An int8
    dec block takes the quantized-query path: per-query symmetric
    quantization in VMEM, int8×int8 MXU dot with int32 accumulation,
    rescale by the per-query scale × the cache's frozen scan_scale
    (scale_ref, SMEM) — the memory-lean DEEP-100M mode's scoring, fused.
    ``metric`` picks the score: L2 (y² − 2ip + q²) or inner product
    (−ip); ``filtered`` expands the list's packed filter words to a lane
    mask and demotes failing slots."""
    G = qg_ref.shape[1]
    # Mosaic lowering: every vector op stays 2-D — q2 rides as a [G, 1]
    # column block and y2/ids as [1, cap] rows, so the masks build from
    # plain 2-D broadcasts (1-D reshapes/transposes crash tpu_compile)
    scores, cand_i = _score_against_list(
        dec_ref[0], qg_ref[0], q2_ref[0], y2_ref[0], ids_ref[0],
        filt_ref[0], scale_ref[0, 0],
        metric=metric, filtered=filtered, scan_dtype=scan_dtype,
    )
    run_v = jnp.full((G, kk), _WORST, jnp.float32)
    run_i = jnp.full((G, kk), -1, jnp.int32)
    v, i = fold_topk(run_v, run_i, scores, cand_i, kk)
    i = jnp.where(jnp.isfinite(v), i, -1)
    vals_ref[0] = v
    out_ids_ref[0] = i


def _scan_paged_kernel(bucket_list_ref, page_slot_ref, dec_ref, y2_ref,
                       ids_ref, qg_ref, q2_ref, scale_ref, vals_ref,
                       out_ids_ref, run_v_ref, run_i_ref, *, kk: int,
                       ppl: int, pr: int, metric: str, scan_dtype: str):
    """Paged probe-major step: grid (B, ppl) walks the bucket's list one
    *page* at a time.  The dec block rides the page-table indirection —
    TWO prefetched scalars compose in its index_map
    (``page_slot[bucket_list[b] * ppl + j]``), so the hot pool's slot
    order is invisible to the kernel body.  y2/ids stay monolithic
    [1, cap] blocks (device-resident sidecars) sliced per page in VMEM;
    the per-query top-kk accumulates across pages in scratch and is
    written once on the last page (the qm kernel's accumulate-then-fold
    shape, folded incrementally so no [G, cap] pool materializes)."""
    G = qg_ref.shape[1]
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _reset():
        run_v_ref[...] = jnp.full((G, kk), _WORST, jnp.float32)
        run_i_ref[...] = jnp.full((G, kk), -1, jnp.int32)

    y2_row = jax.lax.dynamic_slice_in_dim(y2_ref[0], j * pr, pr, axis=1)
    ids_row = jax.lax.dynamic_slice_in_dim(ids_ref[0], j * pr, pr, axis=1)
    scores, cand_i = _score_against_list(
        dec_ref[0], qg_ref[0], q2_ref[0], y2_row, ids_row,
        jnp.zeros((1, 1), jnp.uint32), scale_ref[0, 0],
        metric=metric, filtered=False, scan_dtype=scan_dtype,
    )
    v, i = fold_topk(run_v_ref[...], run_i_ref[...], scores, cand_i, kk)
    run_v_ref[...] = v
    run_i_ref[...] = i

    @pl.when(j == ppl - 1)
    def _emit():
        vf = run_v_ref[...]
        vals_ref[0] = vf
        out_ids_ref[0] = jnp.where(jnp.isfinite(vf), run_i_ref[...], -1)


def paged_scan_supported(list_data, kk: int, filtered: bool) -> bool:
    """Routing gate for the paged probe-major leg: the per-page fold
    caps the candidate pool at ``page_rows`` per step (so ``kk`` may not
    exceed it) and filtered searches keep the XLA schedule (the packed
    word table is capacity-indexed, not page-indexed)."""
    if not isinstance(list_data, PagedLists):
        return False
    pr = list_data.page_rows
    return (not filtered) and kk <= pr and pr % 8 == 0


def _ivf_scan_probe_major_paged(
    bucket_list, q_gathered, q2_gathered, list_data: PagedLists, list_y2,
    list_index, kk, *, metric, scan_dtype, scan_scale, interpret,
):
    """Paged body of :func:`ivf_scan_probe_major` (same contract)."""
    B, G, rot = q_gathered.shape
    L, cap = list_data.shape[:2]
    ppl = list_data.pages_per_list
    pr = list_data.page_rows

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, ppl),
        in_specs=[
            pl.BlockSpec(       # dec: page j of the bucket's list, via
                (1, pr, rot),   # the device page table (slot −1 of a
                                # non-probed padding list clamps to 0;
                                # its scores die on the q2=+inf mask)
                lambda b, j, bl, ps: (
                    jnp.maximum(ps[bl[b] * ppl + j], 0), 0, 0
                ),
            ),
            pl.BlockSpec((1, 1, cap), lambda b, j, bl, ps: (bl[b], 0, 0)),
            pl.BlockSpec((1, 1, cap), lambda b, j, bl, ps: (bl[b], 0, 0)),
            pl.BlockSpec((1, G, rot), lambda b, j, bl, ps: (b, 0, 0)),
            pl.BlockSpec((1, G, 1), lambda b, j, bl, ps: (b, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),       # scan_scale
        ],
        out_specs=[
            pl.BlockSpec((1, G, kk), lambda b, j, bl, ps: (b, 0, 0)),
            pl.BlockSpec((1, G, kk), lambda b, j, bl, ps: (b, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((G, kk), jnp.float32),   # running top-kk values
            pltpu.VMEM((G, kk), jnp.int32),     # running top-kk ids
        ],
    )
    c = ops_cost.ivf_scan_cost(
        B, G, cap, rot, kk, itemsize=list_data.dtype.itemsize
    )
    ops_cost.note("ivf_scan_probe_major_paged", c)
    vals, ids = pl.pallas_call(
        functools.partial(
            _scan_paged_kernel, kk=kk, ppl=ppl, pr=pr, metric=metric,
            scan_dtype=scan_dtype,
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, G, kk), jnp.float32),
            jax.ShapeDtypeStruct((B, G, kk), jnp.int32),
        ],
        cost_estimate=c.as_pallas(),
        interpret=interpret,
    )(
        bucket_list,
        list_data.page_slot,
        list_data.pool,
        list_y2[:, None, :],
        list_index[:, None, :],
        q_gathered,
        q2_gathered[:, :, None],
        jnp.asarray(scan_scale, jnp.float32).reshape(1, 1),
    )
    return vals, ids


@functools.partial(
    jax.jit, static_argnames=("kk", "metric", "scan_dtype", "interpret")
)
def ivf_scan_probe_major(
    bucket_list: jax.Array,   # [B] int32 — list id per bucket
    q_gathered: jax.Array,    # [B, G, rot] f32 — bucket queries (rotated)
    q2_gathered: jax.Array,   # [B, G] f32 — ‖q_rot‖² (+inf at padding)
    list_data: jax.Array,     # [L, cap, rot] f32/bf16/int8 stored rows
    list_y2: jax.Array,       # [L, cap] f32
    list_index: jax.Array,    # [L, cap] int32
    kk: int,
    *,
    metric: str = "sqeuclidean",
    scan_dtype: str = "highest",  # highest | float32 | bfloat16 (float leg)
    list_filter: jax.Array | None = None,  # [L, ceil(cap/32)] uint32
    scan_scale: float = 1.0,  # int8 cache dequant scale (1.0 for floats)
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Returns per-bucket (vals [B, G, kk], ids [B, G, kk]) score partials
    (L2 or −ip per ``metric``) — feed them to
    _common.merge_probe_major_partials.  The caller supplies the
    pre-gathered bucket queries (one [B, G, rot] HBM pass — tiny next to
    the list stream this schedule saves) and, for filtered searches, the
    ``pack_list_filter`` word table.

    A :class:`~raft_tpu.store.paged.PagedLists` ``list_data`` takes the
    paged leg (grid (B, pages_per_list), dec indirected through the
    device page table; gate with :func:`paged_scan_supported`)."""
    if isinstance(list_data, PagedLists):
        assert list_filter is None, "paged pallas leg is unfiltered-only"
        return _ivf_scan_probe_major_paged(
            bucket_list, q_gathered, q2_gathered, list_data, list_y2,
            list_index, kk, metric=metric, scan_dtype=scan_dtype,
            scan_scale=scan_scale, interpret=interpret,
        )
    B, G, rot = q_gathered.shape
    L, cap, _ = list_data.shape
    filtered = list_filter is not None
    if not filtered:
        # single-word dummy rides the same BlockSpec; the kernel skips it
        list_filter = jnp.zeros((L, 1), jnp.uint32)
    cap_w = list_filter.shape[1]

    # 2-D operands indexed by the dynamic list id carry a singleton middle
    # axis: Mosaic requires each block's last two dims to be (8, 128)-
    # divisible OR equal to the array dims, and a (1, cap) block over an
    # [L, cap] array satisfies neither when L is dynamic-selected.  As
    # [L, 1, cap] the block (1, 1, cap) matches the trailing (1, cap)
    # exactly (first real Mosaic compile, round 4).
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B,),
        in_specs=[
            pl.BlockSpec(       # dec: the bucket's list rows (dynamic)
                (1, cap, rot), lambda b, bl: (bl[b], 0, 0)
            ),
            pl.BlockSpec((1, 1, cap), lambda b, bl: (bl[b], 0, 0)),   # y2
            pl.BlockSpec((1, 1, cap), lambda b, bl: (bl[b], 0, 0)),   # ids
            pl.BlockSpec((1, 1, cap_w), lambda b, bl: (bl[b], 0, 0)),  # filt
            pl.BlockSpec((1, G, rot), lambda b, bl: (b, 0, 0)),  # queries
            pl.BlockSpec((1, G, 1), lambda b, bl: (b, 0, 0)),    # q2 column
            pl.BlockSpec(memory_space=pltpu.SMEM),               # scan_scale
        ],
        out_specs=[
            pl.BlockSpec((1, G, kk), lambda b, bl: (b, 0, 0)),
            pl.BlockSpec((1, G, kk), lambda b, bl: (b, 0, 0)),
        ],
    )
    c = ops_cost.ivf_scan_cost(
        B, G, cap, rot, kk, itemsize=list_data.dtype.itemsize
    )
    ops_cost.note("ivf_scan_probe_major", c)
    vals, ids = pl.pallas_call(
        functools.partial(
            _scan_kernel, kk=kk, metric=metric, filtered=filtered,
            scan_dtype=scan_dtype,
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, G, kk), jnp.float32),
            jax.ShapeDtypeStruct((B, G, kk), jnp.int32),
        ],
        cost_estimate=c.as_pallas(),
        interpret=interpret,
    )(
        bucket_list,
        list_data,
        list_y2[:, None, :],
        list_index[:, None, :],
        list_filter[:, None, :],
        q_gathered,
        q2_gathered[:, :, None],
        jnp.asarray(scan_scale, jnp.float32).reshape(1, 1),
    )
    return vals, ids


def _scan_qm_kernel(probes_ref, dec_ref, y2_ref, ids_ref, filt_ref, q_ref,
                    q2_ref, scale_ref, vals_ref, out_ids_ref, s_v, s_i, *,
                    kk: int, metric: str, filtered: bool, scan_dtype: str,
                    P: int, G: int, cap: int, cap_pad: int):
    """One (query-block, probe, member) step of the fused query-major
    scan: score member ``i``'s probe-``p`` list into the block's VMEM
    score scratch; after the block's last (p, i) step, ONE fold over the
    whole [G, P*cap] pool extracts every member's top-kk.  The G-wide
    fold is the point: a per-query fold would waste 7 of 8 sublanes and
    dominate the kernel (measured reasoning in ROUND4_NOTES); batching G
    queries' pools through fold_topk amortizes it G-fold."""
    p = pl.program_id(1)
    i = pl.program_id(2)
    scores, cand_i = _score_against_list(
        dec_ref[0], q_ref[0], q2_ref[0], y2_ref[0], ids_ref[0],
        filt_ref[0], scale_ref[0, 0],
        metric=metric, filtered=filtered, scan_dtype=scan_dtype,
    )                                                    # [1, cap] each
    # scratch rows are lane-padded to cap_pad: merging (G, P, cap) to
    # (G, P*cap) is a Mosaic "unsupported shape cast" whenever cap isn't
    # a lane multiple (real indexes: cap=632), so pad slots carry
    # _WORST/-1 and the aligned pool reshapes for ONE G-wide fold
    if cap_pad > cap:
        scores = jnp.concatenate(
            [scores, jnp.full((1, cap_pad - cap), _WORST, scores.dtype)], 1
        )
        cand_i = jnp.concatenate(
            [cand_i, jnp.full((1, cap_pad - cap), -1, cand_i.dtype)], 1
        )
    s_v[i, p, :] = scores[0]
    s_i[i, p, :] = cand_i[0]

    @pl.when((p == P - 1) & (i == G - 1))
    def _fold():
        pool_v = s_v[...].reshape(G, P * cap_pad)
        pool_i = s_i[...].reshape(G, P * cap_pad)
        run_v = jnp.full((G, kk), _WORST, jnp.float32)
        run_i = jnp.full((G, kk), -1, jnp.int32)
        v, o = fold_topk(run_v, run_i, pool_v, pool_i, kk)
        o = jnp.where(jnp.isfinite(v), o, -1)
        vals_ref[0] = v
        out_ids_ref[0] = o


def _scan_qm_kernel_fid(probes_ref, fid_ref, *rest, **kw):
    """Descriptor-leg adapter: with two prefetched scalars (probes, fid)
    the kernel receives an extra leading ref, but fid only drives the filt
    BlockSpec index map — the body is byte-identical to the single-filter
    schedule (the block already arrived selected)."""
    _scan_qm_kernel(probes_ref, *rest, **kw)


#: query-block width of the fused query-major scan — one full sublane set
_QM_GROUP = 8


#: per-block VMEM scratch ceiling for the query-major kernel — the ONE
#: owner both index dispatches gate on; past it the XLA legs tile better.
#: Tune from the on-chip ivf_scan_ab sweep.
QM_VMEM_BUDGET = 6 * 1024 * 1024


def _cap_pad(cap: int) -> int:
    """Lane-padded scratch row width — the ONE owner of the padding rule
    (scratch rows pad to a 128 multiple so the fold's pool reshape is a
    supported Mosaic relayout; see _scan_qm_kernel)."""
    return -(-cap // 128) * 128


def qm_scratch_bytes(n_probes: int, cap: int) -> int:
    """VMEM score+id scratch the query-major kernel allocates per block —
    the dispatch gates on this (one owner for the formula and _QM_GROUP).
    cap counts lane-padded (scratch rows are padded to a 128 multiple)."""
    return 2 * _QM_GROUP * n_probes * _cap_pad(cap) * 4


def qm_query_tile(n_probes: int) -> int:
    """Host-level query tile for the fused query-major dispatch: bounds
    the scalar-prefetch operand (q_tile·n_probes int32 must stay
    SMEM-small), rounded to the kernel group width."""
    return max(_QM_GROUP, min(4096, (32_768 // max(1, n_probes)) // 8 * 8))


@functools.partial(
    jax.jit, static_argnames=("kk", "metric", "scan_dtype", "interpret")
)
def ivf_scan_query_major(
    probes: jax.Array,        # [Q, P] int32 — per-query probed list ids
    q_rot: jax.Array,         # [Q, rot] f32 — rotated queries
    q2: jax.Array,            # [Q] f32 — ‖q_rot‖² (+inf marks padding)
    list_data: jax.Array,     # [L, cap, rot] f32/bf16/int8 stored rows
    list_y2: jax.Array,       # [L, cap] f32
    list_index: jax.Array,    # [L, cap] int32
    kk: int,
    *,
    metric: str = "sqeuclidean",
    scan_dtype: str = "highest",
    list_filter: jax.Array | None = None,  # [L, ceil(cap/32)] uint32
    query_fid: jax.Array | None = None,    # [Q] int32 — ragged filter ids
    scan_scale: float = 1.0,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Fused query-major IVF scan: each query's probed lists stream
    straight from the index into VMEM (the XLA schedule's materialized
    [t, p, cap, rot] gather copy and [t, p, cap] score tensor never
    exist), scores accumulate in a per-block VMEM scratch, and one
    G-wide fold per query block extracts the top-kk.  Returns
    (vals [Q, kk], ids [Q, kk]) raw score partials — same conventions as
    the XLA query-major leg pre-postprocess.  Q must be a multiple of
    the group width (pad with q2=+inf rows; their outputs are -1/inf).

    Ragged descriptor leg: with ``query_fid`` (and ``list_filter`` a
    ``pack_list_filter_table`` [F, L, cap_w] table) each query's filter id
    rides as a second prefetched scalar that only the filt BlockSpec index
    map consumes — query i of step (qb, p) DMAs word block
    ``fid[qb·G+i]·L + probes[...]`` of the flattened [F·L, 1, cap_w]
    table.  The kernel body is unchanged, so heterogeneous-filter batches
    keep the fused path with one executable.

    VMEM budget: the scratch holds 2·G·P·cap_pad·4 bytes (cap lane-padded
    to a 128 multiple; ``qm_scratch_bytes`` is the owner) — callers gate
    on this (see ivf_pq's dispatch) and fall back to XLA past it."""
    Q, P = probes.shape
    L, cap, rot = list_data.shape
    G = _QM_GROUP
    if Q % G:
        raise ValueError(f"Q={Q} must be a multiple of {G} (pad upstream)")
    if query_fid is not None:
        if list_filter is None or list_filter.ndim != 3:
            raise ValueError(
                "query_fid requires a pack_list_filter_table [F, L, cap_w] "
                "list_filter"
            )
        F, _, cap_w = list_filter.shape
        cap_pad = _cap_pad(cap)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(Q // G, P, G),
            in_specs=[
                pl.BlockSpec(       # dec: member i's probe-p list (dynamic)
                    (1, cap, rot),
                    lambda qb, p, i, pr, fid: (pr[(qb * G + i) * P + p], 0, 0),
                ),
                pl.BlockSpec(
                    (1, 1, cap),
                    lambda qb, p, i, pr, fid: (pr[(qb * G + i) * P + p], 0, 0),
                ),
                pl.BlockSpec(
                    (1, 1, cap),
                    lambda qb, p, i, pr, fid: (pr[(qb * G + i) * P + p], 0, 0),
                ),
                pl.BlockSpec(       # filt: the member's OWN filter plane
                    (1, 1, cap_w),
                    lambda qb, p, i, pr, fid: (
                        fid[qb * G + i] * L + pr[(qb * G + i) * P + p],
                        0,
                        0,
                    ),
                ),
                pl.BlockSpec(       # member i's query row
                    (1, 1, rot), lambda qb, p, i, pr, fid: (qb * G + i, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, 1), lambda qb, p, i, pr, fid: (qb * G + i, 0, 0)
                ),
                pl.BlockSpec(memory_space=pltpu.SMEM),   # scan_scale
            ],
            out_specs=[
                pl.BlockSpec((1, G, kk), lambda qb, p, i, pr, fid: (qb, 0, 0)),
                pl.BlockSpec((1, G, kk), lambda qb, p, i, pr, fid: (qb, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((G, P, cap_pad), jnp.float32),
                pltpu.VMEM((G, P, cap_pad), jnp.int32),
            ],
        )
        c = ops_cost.ivf_scan_cost(
            Q * P, 1, cap, rot, kk, itemsize=list_data.dtype.itemsize
        )
        ops_cost.note("ivf_scan_query_major", c)
        vals, ids = pl.pallas_call(
            functools.partial(
                _scan_qm_kernel_fid, kk=kk, metric=metric, filtered=True,
                scan_dtype=scan_dtype, P=P, G=G, cap=cap, cap_pad=cap_pad,
            ),
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((Q // G, G, kk), jnp.float32),
                jax.ShapeDtypeStruct((Q // G, G, kk), jnp.int32),
            ],
            cost_estimate=c.as_pallas(),
            interpret=interpret,
        )(
            probes.reshape(-1),
            jnp.asarray(query_fid, jnp.int32).reshape(-1),
            list_data,
            list_y2[:, None, :],
            list_index[:, None, :],
            list_filter.reshape(F * L, 1, cap_w),
            q_rot[:, None, :],
            q2[:, None, None],
            jnp.asarray(scan_scale, jnp.float32).reshape(1, 1),
        )
        return vals.reshape(Q, kk), ids.reshape(Q, kk)
    filtered = list_filter is not None
    if not filtered:
        list_filter = jnp.zeros((L, 1), jnp.uint32)
    cap_w = list_filter.shape[1]
    cap_pad = _cap_pad(cap)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Q // G, P, G),
        in_specs=[
            pl.BlockSpec(       # dec: member i's probe-p list (dynamic)
                (1, cap, rot),
                lambda qb, p, i, pr: (pr[(qb * G + i) * P + p], 0, 0),
            ),
            pl.BlockSpec(
                (1, 1, cap),
                lambda qb, p, i, pr: (pr[(qb * G + i) * P + p], 0, 0),
            ),
            pl.BlockSpec(
                (1, 1, cap),
                lambda qb, p, i, pr: (pr[(qb * G + i) * P + p], 0, 0),
            ),
            pl.BlockSpec(
                (1, 1, cap_w),
                lambda qb, p, i, pr: (pr[(qb * G + i) * P + p], 0, 0),
            ),
            pl.BlockSpec(       # member i's query row
                (1, 1, rot), lambda qb, p, i, pr: (qb * G + i, 0, 0)
            ),
            pl.BlockSpec((1, 1, 1), lambda qb, p, i, pr: (qb * G + i, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),       # scan_scale
        ],
        out_specs=[
            pl.BlockSpec((1, G, kk), lambda qb, p, i, pr: (qb, 0, 0)),
            pl.BlockSpec((1, G, kk), lambda qb, p, i, pr: (qb, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((G, P, cap_pad), jnp.float32),
            pltpu.VMEM((G, P, cap_pad), jnp.int32),
        ],
    )
    c = ops_cost.ivf_scan_cost(
        Q * P, 1, cap, rot, kk, itemsize=list_data.dtype.itemsize
    )
    ops_cost.note("ivf_scan_query_major", c)
    vals, ids = pl.pallas_call(
        functools.partial(
            _scan_qm_kernel, kk=kk, metric=metric, filtered=filtered,
            scan_dtype=scan_dtype, P=P, G=G, cap=cap, cap_pad=cap_pad,
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((Q // G, G, kk), jnp.float32),
            jax.ShapeDtypeStruct((Q // G, G, kk), jnp.int32),
        ],
        cost_estimate=c.as_pallas(),
        interpret=interpret,
    )(
        probes.reshape(-1),
        list_data,
        list_y2[:, None, :],
        list_index[:, None, :],
        list_filter[:, None, :],
        q_rot[:, None, :],
        q2[:, None, None],
        jnp.asarray(scan_scale, jnp.float32).reshape(1, 1),
    )
    return vals.reshape(Q, kk), ids.reshape(Q, kk)
