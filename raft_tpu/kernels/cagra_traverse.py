"""Pallas fused CAGRA hop: frontier expansion + scoring + dedup + merge.

``neighbors/cagra.py``'s beam search runs a ``lax.while_loop`` whose body
is a gather-heavy XLA chain: gather the parents' neighbor lists, gather
(and cast) the candidates' dataset rows into a materialized
[tile, width·deg, d] HBM copy, score on the MXU, dedup by broadcast
membership, then a global ``select_k`` merge back into the [tile, itopk]
candidate buffer.  The dataset-row gather copy plus the full-width merge
sort are the hop's dominant HBM traffic — exactly the irregular workload
the "Ragged Paged Attention" line of work (PAPERS.md) shows hand-written
TPU kernels beat dense HLO at.

This kernel fuses one hop over a ``grid=(tile, width)`` schedule:

- the *scalar-prefetched frontier ids* drive a dynamic-BlockSpec DMA of
  each parent's neighbor list into VMEM (the ivf_scan pattern), and the
  candidate ids (a tiny pre-gathered int table riding as a second
  prefetched scalar) drive per-row in-kernel DMAs of the candidates'
  dataset rows — the [tile, width·deg, d] gather copy never exists;
- MXU scoring ([1, d] × [deg, d]ᵀ, f32 accumulate at HIGHEST precision,
  matching the XLA hop's ``_query_distance`` einsum);
- visited-dedup by membership against the VMEM-resident merged buffer
  plus a strict-upper within-step mask (the reference's visited-hashmap
  role, detail/cagra/hashmap.hpp);
- itopk buffer maintenance in VMEM via ``toolkit.fold_topk``, with the
  same resident-wins tie discipline as the XLA merge (buffer entries
  occupy the pool's first positions).

The merged buffer lives in scratch across the ``width`` steps of one
query; the final step recovers explored flags by membership against the
input buffer (buffer ids are unique, so the flag transfers exactly) and
writes the three state planes once.

The hop is bit-equivalent to the XLA body up to value ties at the
buffer's eviction boundary (an evicted-then-reencountered id can displace
an equal-valued different id) — the parity tests therefore gate on
recall equivalence, the same gate the XLA legs hold each other to.
Filtered search keeps the XLA hop (the result-buffer side-merge needs
the raw candidate distances; see docs/kernels.md for the dispatch
matrix).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.kernels.toolkit import fold_topk
from raft_tpu.ops import cost as ops_cost
from raft_tpu.store.paged import PagedRows

_INF = float("inf")

#: widest internal buffer the fused hop serves — filtered searches widen
#: itopk past this (they keep the XLA hop anyway) and the fold's O(itopk²)
#: rounds stop paying past it
MAX_ITOPK = 512


def traverse_supported(dataset, itopk: int) -> bool:
    """Routing gate for the fused hop: dense float dataset (f32/bf16 —
    rows upcast in VMEM after the DMA) at fold-friendly buffer widths.
    VPQ datasets decode on gather (no raw rows to DMA) and int8 datasets
    lack a dequant scale — both keep the XLA hop.  A paged dataset
    (:class:`~raft_tpu.store.paged.PagedRows`) rides the same per-row DMA
    with one extra prefetched-scalar page-table hop."""
    return (
        (isinstance(dataset, jax.Array) or isinstance(dataset, PagedRows))
        and jnp.dtype(dataset.dtype)
        in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16))
        and 0 < itopk <= MAX_ITOPK
    )


def _hop_kernel(par_ref, cand_ref, g_blk, q_blk, bd_blk, bi_blk, be_blk,
                dataset_ref, od_blk, oi_blk, oe_blk, rows_s, md_s, mi_s,
                sem, *, metric: str, deg: int, itopk: int, width: int,
                d: int, page_rows=None, ps_ref=None):
    """One (query, parent) step.  Scratch (rows_s, md_s, mi_s) persists
    across the ``width`` steps of a query; w==0 seeds the merged buffer
    from the input planes and w==width−1 writes the merged state once.

    ``page_rows``/``ps_ref`` select the paged leg: ``dataset_ref`` is then
    the HBM page pool ``[slots, page_rows, d]`` and each candidate row DMA
    translates its global id through the prefetched ``page_slot`` table —
    the "one more prefetched indirection" the page table costs."""
    t = pl.program_id(0)
    w = pl.program_id(1)
    pid = par_ref[t * width + w]

    @pl.when(w == 0)
    def _seed():
        md_s[...] = bd_blk[0]
        # invariant from the XLA wrapper: buf_i is −1 wherever buf_d is
        # +inf, so membership below never matches a stale id
        mi_s[...] = bi_blk[0]

    # ---- candidate dataset rows: per-row DMA driven by the prefetched
    # candidate-id table (invalid ids clamp to row 0; scores masked below)
    def load(j, _):
        cid = jnp.maximum(cand_ref[(t * width + w) * deg + j], 0)
        if page_rows is None:
            src = dataset_ref.at[pl.ds(cid, 1), :]
        else:
            pg = cid // page_rows
            slot = jnp.maximum(ps_ref[pg], 0)
            src = dataset_ref.at[slot, pl.ds(cid - pg * page_rows, 1), :]
        cp = pltpu.make_async_copy(src, rows_s.at[pl.ds(j, 1), :], sem)
        cp.start()
        cp.wait()
        return 0

    lax.fori_loop(0, deg, load, 0)

    rows = rows_s[...].astype(jnp.float32)                   # [deg, d]
    q = q_blk[0].astype(jnp.float32)                         # [1, d]
    ip = lax.dot_general(
        q, rows, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=lax.Precision.HIGHEST,
    )                                                        # [1, deg]
    if metric == "inner_product":
        cd = -ip
    else:
        # v² via an MXU ones-contraction keeps every vector op 2-D
        v2 = lax.dot_general(
            jnp.full((1, d), 1.0, jnp.float32), rows * rows,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                    # [1, deg]
        q2 = jnp.sum(q * q, axis=1, keepdims=True)           # [1, 1]
        cd = jnp.maximum(q2 + v2 - 2.0 * ip, 0.0)

    # ---- visited dedup: membership vs the live merged buffer (covers the
    # original buffer AND earlier parents' survivors) + a strict-upper
    # within-step mask for duplicate neighbors in one list
    cand = g_blk[0]                                          # [1, deg]
    m_i = mi_s[...]                                          # [1, itopk]
    in_buf = jnp.any(cand[:, :, None] == m_i[:, None, :], axis=2)
    pi = lax.broadcasted_iota(jnp.int32, (1, deg, deg), 1)
    pj = lax.broadcasted_iota(jnp.int32, (1, deg, deg), 2)
    dup = jnp.any(
        (cand[:, :, None] == cand[:, None, :]) & (pi < pj), axis=1
    )
    bad = (cand < 0) | in_buf | dup | (pid < 0)
    cd = jnp.where(bad, _INF, cd)
    cand = jnp.where(bad, -1, cand)

    # ---- fold into the merged buffer: residents ride the pool's first
    # positions, so fold_topk's first-position tie-break keeps the XLA
    # merge's resident-wins discipline
    v, i = fold_topk(md_s[...], m_i, cd, cand, itopk)
    # the +inf slots a short pool leaves behind must not carry ids (they
    # would shadow later finite copies) — same fixup as the XLA hop
    i = jnp.where(jnp.isfinite(v), i, -1)
    md_s[...] = v
    mi_s[...] = i

    @pl.when(w == width - 1)
    def _finish():
        mv = md_s[...]
        mi = mi_s[...]
        # explored flags transfer by membership against the input buffer
        # (ids unique): new candidates are unexplored, +inf slots explored
        hit = (mi[:, :, None] == bi_blk[0][:, None, :]) & (
            be_blk[0][:, None, :] != 0
        )
        exp = jnp.any(hit, axis=2) | ~jnp.isfinite(mv)
        od_blk[0] = mv
        oi_blk[0] = mi
        oe_blk[0] = exp.astype(jnp.int32)


def _hop_kernel_paged(par_ref, cand_ref, ps_ref, g_blk, q_blk, bd_blk,
                      bi_blk, be_blk, pool_ref, od_blk, oi_blk, oe_blk,
                      rows_s, md_s, mi_s, sem, *, metric: str, deg: int,
                      itopk: int, width: int, d: int, page_rows: int):
    """Paged entry point: same hop body, with the page-slot table riding
    as a third prefetched scalar ahead of the grid operands."""
    _hop_kernel(
        par_ref, cand_ref, g_blk, q_blk, bd_blk, bi_blk, be_blk, pool_ref,
        od_blk, oi_blk, oe_blk, rows_s, md_s, mi_s, sem, metric=metric,
        deg=deg, itopk=itopk, width=width, d=d, page_rows=page_rows,
        ps_ref=ps_ref,
    )


def cagra_fused_hop(
    dataset,                 # [n, d] f32/bf16 jax.Array or PagedRows
    graph: jax.Array,        # [n, deg] int32
    queries: jax.Array,      # [tile, d] f32
    parents: jax.Array,      # [tile, width] int32, −1 = no parent
    buf_d: jax.Array,        # [tile, itopk] f32 (+inf empty slots)
    buf_i: jax.Array,        # [tile, itopk] int32 (−1 at +inf slots)
    explored: jax.Array,     # [tile, itopk] bool (parents pre-marked)
    *,
    metric: str,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One fused hop; returns the merged (buf_d, buf_i, explored).
    Call from inside the search while-loop — everything here traces into
    the enclosing jit."""
    tile, itopk = buf_d.shape
    width = parents.shape[1]
    paged = isinstance(dataset, PagedRows)
    n, d = dataset.shape
    deg = graph.shape[1]
    # candidate-id table for the DMA scalars: a [tile, width, deg] int32
    # gather — 4 bytes/candidate next to the d·itemsize/candidate row
    # gather the kernel eliminates
    cand = graph[jnp.clip(parents, 0, n - 1)]
    cand = jnp.where(parents[:, :, None] < 0, -1, cand)

    c = ops_cost.cagra_traverse_cost(
        tile, width, deg, d, itopk, itemsize=dataset.dtype.itemsize
    )
    ops_cost.note("cagra_traverse", c)

    # index_maps take *rest so the same lambdas serve 2 (dense) or 3
    # (paged: + page_slot) prefetched scalar operands
    def _nbr_map(t, w, par, *rest):
        return jnp.maximum(par[t * width + w], 0), 0, 0

    def _tile_map(t, w, *rest):
        return t, 0, 0

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3 if paged else 2,
        grid=(tile, width),
        in_specs=[
            # the parent's neighbor list (dynamic)
            pl.BlockSpec((1, 1, deg), _nbr_map),
            pl.BlockSpec((1, 1, d), _tile_map),
            pl.BlockSpec((1, 1, itopk), _tile_map),
            pl.BlockSpec((1, 1, itopk), _tile_map),
            pl.BlockSpec((1, 1, itopk), _tile_map),
            pl.BlockSpec(memory_space=pltpu.ANY),   # dataset/pool in HBM
        ],
        out_specs=[
            pl.BlockSpec((1, 1, itopk), _tile_map),
            pl.BlockSpec((1, 1, itopk), _tile_map),
            pl.BlockSpec((1, 1, itopk), _tile_map),
        ],
        scratch_shapes=[
            pltpu.VMEM((deg, d), dataset.dtype),    # candidate rows
            pltpu.VMEM((1, itopk), jnp.float32),    # merged values
            pltpu.VMEM((1, itopk), jnp.int32),      # merged ids
            pltpu.SemaphoreType.DMA(()),
        ],
    )
    if paged:
        kern = functools.partial(
            _hop_kernel_paged, metric=metric, deg=deg, itopk=itopk,
            width=width, d=d, page_rows=dataset.page_rows,
        )
        scalars = (
            parents.reshape(-1).astype(jnp.int32),
            cand.reshape(-1).astype(jnp.int32),
            dataset.page_slot.astype(jnp.int32),
        )
        ds_operand = dataset.pool
    else:
        kern = functools.partial(
            _hop_kernel, metric=metric, deg=deg, itopk=itopk,
            width=width, d=d,
        )
        scalars = (
            parents.reshape(-1).astype(jnp.int32),
            cand.reshape(-1).astype(jnp.int32),
        )
        ds_operand = dataset
    od, oi, oe = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((tile, 1, itopk), jnp.float32),
            jax.ShapeDtypeStruct((tile, 1, itopk), jnp.int32),
            jax.ShapeDtypeStruct((tile, 1, itopk), jnp.int32),
        ],
        cost_estimate=c.as_pallas(),
        interpret=interpret,
    )(
        *scalars,
        graph.reshape(n, 1, deg),
        queries[:, None, :],
        buf_d[:, None, :],
        buf_i[:, None, :],
        explored[:, None, :].astype(jnp.int32),
        ds_operand,
    )
    return od[:, 0, :], oi[:, 0, :], oe[:, 0, :] != 0
