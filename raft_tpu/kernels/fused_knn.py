"""Fused tiled kNN: distance tile on the MXU + running top-k in VMEM.

The reference's ``tiled_brute_force_knn`` materializes each distance tile
in device memory and then runs select_k over it
(ref: cpp/include/raft/neighbors/detail/knn_brute_force.cuh:60-300); its
``fusedL2Knn`` fast path fuses the two for small dims
(ref: cpp/include/raft/spatial/knn/detail/fused_l2_knn-inl.cuh).

TPU design: one Pallas kernel with a (query-tile, dataset-tile) grid,
dataset-tile innermost.  Each step computes the partial-score tile

    L2: scores = ‖x‖² − 2·q@xᵀ        (the per-query ‖q‖² term is rank-
                                       invariant and added by the caller)
    IP: scores = −q@xᵀ                 (select-min on negated similarity)

on the MXU, then folds it into a running top-k held in the *output* block,
which stays resident in VMEM across all dataset tiles of one query tile
(revisited out-block accumulation).  The [n_q, n] score matrix never exists
in HBM — that is the bandwidth win over the XLA formulation.

Top-k maintenance is k rounds of min-extraction over the concatenated
[running-k | tile] candidates (no sort network needed for the k ≤ 128
regime this kernel serves; larger k falls back to the XLA path).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.kernels.toolkit import col_ids_tile, fold_topk
from raft_tpu.ops import cost as ops_cost

_WORST = float("inf")


def _fused_knn_kernel(q_ref, x_ref, xx_ref, vals_ref, idx_ref, *, k: int,
                      tile_n: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        vals_ref[:] = jnp.full_like(vals_ref, _WORST)
        idx_ref[:] = jnp.zeros_like(idx_ref)

    qt = q_ref.shape[0]
    # MXU: [qt, d] @ [d, tile_n] — scores are partial L2 (or negated IP)
    # HIGHEST: match the XLA distance paths (pairwise._PREC) — the MXU's
    # default bf16-accumulate shuffles near-tie neighbor ranks
    dots = jax.lax.dot_general(
        q_ref[:], x_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    scores = xx_ref[0, :][None, :] - 2.0 * dots  # xx = +inf on padded rows

    col_ids = col_ids_tile(qt, tile_n, j * tile_n)
    # fold the fresh tile into the VMEM-resident queue (toolkit.fold_topk —
    # the warpsort-queue analog)
    vals, idx = fold_topk(vals_ref[:], idx_ref[:], scores, col_ids, k)
    vals_ref[:] = vals
    idx_ref[:] = idx


@functools.partial(
    jax.jit,
    static_argnames=("k", "mode", "tile_q", "tile_n", "interpret"),
)
def fused_l2_topk(
    queries: jax.Array,
    dataset: jax.Array,
    dataset_sqnorms: jax.Array,
    k: int,
    *,
    mode: str = "l2",          # "l2" (partial sq-L2) | "ip" (negated IP)
    tile_q: int = 256,
    tile_n: int = 512,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (partial scores [n_q, k], indices [n_q, k]), ascending.

    ``l2`` scores are ‖x‖²−2q·x (add ‖q‖² for true sq-L2); ``ip`` scores
    are −⟨q,x⟩.  Ranking matches the exact metric either way.
    """
    if k > 128:
        raise ValueError(f"fused_l2_topk serves k<=128, got {k}")
    n_q, d = queries.shape
    n = dataset.shape[0]
    k_pad = 128

    # pad every axis to tile multiples; zero-padded dims are metric-neutral
    d_pad = (-d) % 128
    q_pad = (-n_q) % tile_q
    n_pad = (-n) % tile_n
    q = jnp.pad(queries.astype(jnp.float32), ((0, q_pad), (0, d_pad)))
    x = jnp.pad(dataset.astype(jnp.float32), ((0, n_pad), (0, d_pad)))
    if mode == "l2":
        xx = jnp.pad(
            dataset_sqnorms.astype(jnp.float32), (0, n_pad),
            constant_values=jnp.inf,
        )
    elif mode == "ip":
        # scores = -q·x: bake the "norm" row to +inf only on padded rows
        xx = jnp.pad(jnp.zeros((n,), jnp.float32), (0, n_pad),
                     constant_values=jnp.inf)
        x = x * 0.5  # so xx - 2·q@x = -q·x on real rows
    else:
        raise ValueError(f"mode must be 'l2' or 'ip', got {mode!r}")
    xx = xx[None, :]

    grid = ((n_q + q_pad) // tile_q, (n + n_pad) // tile_n)
    kernel = functools.partial(_fused_knn_kernel, k=k, tile_n=tile_n)
    c = ops_cost.fused_knn_cost(n_q, n, d, k)
    ops_cost.note("fused_knn", c)
    vals, idx = pl.pallas_call(
        kernel,
        grid=grid,
        cost_estimate=c.as_pallas(),
        in_specs=[
            pl.BlockSpec((tile_q, d + d_pad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, d + d_pad), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tile_q, k_pad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_q, k_pad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_q + q_pad, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((n_q + q_pad, k_pad), jnp.int32),
        ],
        interpret=interpret,
    )(q, x, xx)
    return vals[:n_q, :k], idx[:n_q, :k]
