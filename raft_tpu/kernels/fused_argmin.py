"""Fused L2 1-NN (distance + argmin) — the k-means inner loop.

The reference never materializes the [n, n_clusters] distance matrix for
predict: ``fusedL2NN`` computes the arg-min inside the pairwise-distance
kernel (ref: cpp/include/raft/distance/fused_l2_nn-inl.cuh:79-194, used by
cluster/detail/kmeans_balanced.cuh:83-164 ``predict_core``).

TPU design: grid over (row-tile, center-tile), center-tile innermost; the
running (min score, argmin id) pair lives in the revisited output block in
VMEM.  Scores are partial sq-L2 (‖c‖²−2x·c — the ‖x‖² term is argmin-
invariant) computed on the MXU.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops import cost as ops_cost

_WORST = float("inf")


def _fused_argmin_kernel(x_ref, c_ref, cc_ref, val_ref, idx_ref, *, tile_c: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        val_ref[:] = jnp.full_like(val_ref, _WORST)
        idx_ref[:] = jnp.zeros_like(idx_ref)

    nt = x_ref.shape[0]
    # HIGHEST: match the XLA distance paths (pairwise._PREC) — default
    # MXU precision flips argmins on near-tie centers
    dots = jax.lax.dot_general(
        x_ref[:], c_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    scores = cc_ref[0, :][None, :] - 2.0 * dots       # [nt, tile_c]

    m = jnp.min(scores, axis=1)
    pos = jax.lax.broadcasted_iota(jnp.int32, (nt, tile_c), 1)
    first = jnp.min(jnp.where(scores == m[:, None], pos, tile_c), axis=1)
    cand_i = j * tile_c + first

    better = m < val_ref[:, 0]
    val_ref[:, 0] = jnp.where(better, m, val_ref[:, 0])
    idx_ref[:, 0] = jnp.where(better, cand_i, idx_ref[:, 0])


@functools.partial(
    jax.jit, static_argnames=("tile_rows", "tile_c", "interpret")
)
def fused_l2_argmin(
    x: jax.Array,
    centers: jax.Array,
    center_sqnorms: jax.Array,
    *,
    tile_rows: int = 512,
    tile_c: int = 128,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (partial scores [n], argmin ids [n]); scores are ‖c‖²−2x·c
    (add ‖x‖² for true sq-L2 — the ranking is identical)."""
    n, d = x.shape
    d_pad = (-d) % 128
    n_pad = (-n) % tile_rows
    c_pad = (-centers.shape[0]) % tile_c
    xp = jnp.pad(x.astype(jnp.float32), ((0, n_pad), (0, d_pad)))
    cp = jnp.pad(centers.astype(jnp.float32), ((0, c_pad), (0, d_pad)))
    cc = jnp.pad(center_sqnorms.astype(jnp.float32), (0, c_pad),
                 constant_values=jnp.inf)[None, :]

    grid = ((n + n_pad) // tile_rows, (centers.shape[0] + c_pad) // tile_c)
    c = ops_cost.fused_argmin_cost(n, centers.shape[0], d)
    ops_cost.note("fused_argmin", c)
    val, idx = pl.pallas_call(
        functools.partial(_fused_argmin_kernel, tile_c=tile_c),
        grid=grid,
        cost_estimate=c.as_pallas(),
        in_specs=[
            pl.BlockSpec((tile_rows, d + d_pad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_c, d + d_pad), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_c), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tile_rows, 128), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_rows, 128), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n + n_pad, 128), jnp.float32),
            jax.ShapeDtypeStruct((n + n_pad, 128), jnp.int32),
        ],
        interpret=interpret,
    )(xp, cp, cc)
    return val[:n, 0], idx[:n, 0]
