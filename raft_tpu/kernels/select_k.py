"""Pallas per-row k-selection: VMEM-resident masked-extraction top-k.

``ops.matrix.select_k`` is the single most load-bearing primitive in the
library (ref: matrix/detail/select_radix.cuh, select_warpsort.cuh — the
reference spends two whole CUDA kernel families on it).  Its XLA
formulations materialize a full-width sort in HBM: ``lax.top_k`` lowers to
a sort-based TopK, and the tie-stable merge variant
(``select_k_stable``) is a two-key full-row ``lax.sort``.  At serving
merge widths (a few hundred to a few thousand candidates, k ≤ 128) that
sort dominates the merge legs — the cross-shard gather merge, the tiled
brute-force merges, and the ragged ``mask_row_k`` path all pay it.

This kernel keeps the whole row in VMEM and runs k rounds of masked
min-extraction (the warp-select idea expressed as VPU-wide ops):

  round t:  m      = min over not-yet-removed values
            tiebrk = min tie key among the entries attaining m
            pick   = first position attaining (m, tiebrk)
            out[t] = (m, payload[pick]);  removed |= pick

O(k·n) VPU work with no sort network, one HBM read of the row and one
k-wide write — the same trade ``toolkit.fold_topk`` makes, but with a
*removal mask* instead of overwrite-with-worst so legitimate +inf
candidates (sentinel pads from upstream merges) are never re-extracted.

Both tie-break disciplines ride one kernel body — the wrapper picks the
tie key:

- **positional** (parity with ``lax.top_k``'s lowest-index-wins): tie key
  = column position, payload = ``input_indices`` (or the position);
- **stable** (parity with ``select_k_stable``'s smallest-id-wins): tie
  key = ids with negatives remapped past every real id, payload = ids
  with negatives as −1.

Padding: rows pad to the sublane quantum and columns to the lane quantum
with (+inf, worst tie, −1) slots; a pad can never win a round while a
real candidate remains, and k ≤ n real candidates always remain.
Validated in interpret mode on CPU (exact-match vs both XLA paths) plus a
TPU-gated compile test.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from raft_tpu.kernels.toolkit import LANE, SUBLANE, round_up
from raft_tpu.ops import cost as ops_cost

_INF = float("inf")
_SENTINEL = 2**31 - 1

#: widest row the VMEM-resident select serves — past it matrix.select_k's
#: chunked tournament (narrow sorts) tiles better and the O(k·n) rounds
#: stop paying for themselves
MAX_N = 8192
#: deepest k — matches the serving regime (and fold_topk's k ≤ 128 trade)
MAX_K = 128

_ROW_BLOCK = SUBLANE


def select_k_supported(n: int, k: int, dtype) -> bool:
    """Routing gate for ``ops.matrix.select_k`` / ``select_k_stable``:
    float rows (f32/bf16 — compared in exact f32 upcast) at VMEM-resident
    widths.  Integer rows keep matrix.py's exact argsort/int64 paths."""
    dt = jnp.dtype(dtype)
    return (
        dt in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16))
        and 0 < k <= MAX_K
        and k <= n <= MAX_N
    )


def _select_kernel(v_ref, tie_ref, pay_ref, out_v_ref, out_i_ref, *,
                   k: int, n_pad: int):
    """One row block: k masked min-extraction rounds.  The removal mask
    (not overwrite-with-worst) is what makes +inf a legal candidate value:
    a removed entry can never re-win even when the running min reaches
    +inf, so sentinel-padded merge rows select exactly like the XLA sort."""
    v = v_ref[...]
    tie = tie_ref[...]
    pay = pay_ref[...]
    rows = v.shape[0]
    pos = jax.lax.broadcasted_iota(jnp.int32, (rows, n_pad), 1)

    def extract(t, carry):
        removed, out_v, out_i = carry
        eff = jnp.where(removed, _INF, v)
        m = jnp.min(eff, axis=1)
        # removed entries sit at +inf; exclude them so an all-inf tail
        # round still picks a fresh entry
        is_min = (eff == m[:, None]) & ~removed
        sel_tie = jnp.min(jnp.where(is_min, tie, _SENTINEL), axis=1)
        cand = is_min & (tie == sel_tie[:, None])
        first = jnp.min(jnp.where(cand, pos, n_pad), axis=1)
        pick = pos == first[:, None]
        sel_pay = jnp.sum(jnp.where(pick, pay, 0), axis=1)
        hole = jax.lax.broadcasted_iota(jnp.int32, (rows, k), 1) == t
        out_v = jnp.where(hole, m[:, None], out_v)
        out_i = jnp.where(hole, sel_pay[:, None], out_i)
        return removed | pick, out_v, out_i

    removed0 = jnp.zeros((rows, n_pad), jnp.bool_)
    out_v0 = jnp.full((rows, k), _INF, jnp.float32)
    out_i0 = jnp.full((rows, k), -1, jnp.int32)
    _, out_v, out_i = jax.lax.fori_loop(
        0, k, extract, (removed0, out_v0, out_i0)
    )
    out_v_ref[...] = out_v
    out_i_ref[...] = out_i


def select_k_pallas(
    scores: jax.Array,
    k: int,
    *,
    select_min: bool = True,
    stable: bool = False,
    input_indices: Optional[jax.Array] = None,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Per-row top-k with the fused VMEM kernel.  ``stable=False`` is
    exact-match with ``matrix.select_k``'s float path (lowest position
    wins ties); ``stable=True`` with ``matrix.select_k_stable`` (smallest
    id wins, negative ids lose every tie and surface as −1).  Output rows
    are sorted (ascending for ``select_min``) by construction — each
    round extracts the global remaining min."""
    rows, n = scores.shape
    if not select_k_supported(n, k, scores.dtype):
        raise ValueError(
            f"select_k_pallas unsupported shape/dtype: n={n} k={k} "
            f"{scores.dtype}"
        )
    v = scores.astype(jnp.float32)
    if not select_min:
        v = -v
    n_pad = round_up(max(n, LANE), LANE)
    r_pad = round_up(max(rows, 1), _ROW_BLOCK)
    v = jnp.pad(
        v, ((0, r_pad - rows), (0, n_pad - n)), constant_values=_INF
    )
    pos = jax.lax.broadcasted_iota(jnp.int32, (r_pad, n_pad), 1)
    ids = None
    if input_indices is not None:
        ids = jnp.broadcast_to(
            input_indices.astype(jnp.int32), (rows, n)
        )
        ids = jnp.pad(
            ids, ((0, r_pad - rows), (0, n_pad - n)), constant_values=-1
        )
    if stable:
        base = ids if ids is not None else jnp.where(pos < n, pos, -1)
        tie = jnp.where(base < 0, _SENTINEL, base)
        pay = jnp.where(base < 0, -1, base)
    else:
        # pad positions exceed every real position, so pads lose the
        # positional tie-break among equal (+inf) values by construction
        tie = pos
        pay = ids if ids is not None else pos

    c = ops_cost.select_k_cost(r_pad, n_pad, k)
    ops_cost.note("select_k", c)
    out_v, out_i = pl.pallas_call(
        functools.partial(_select_kernel, k=k, n_pad=n_pad),
        grid=(r_pad // _ROW_BLOCK,),
        in_specs=[
            pl.BlockSpec((_ROW_BLOCK, n_pad), lambda r: (r, 0)),
            pl.BlockSpec((_ROW_BLOCK, n_pad), lambda r: (r, 0)),
            pl.BlockSpec((_ROW_BLOCK, n_pad), lambda r: (r, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_ROW_BLOCK, k), lambda r: (r, 0)),
            pl.BlockSpec((_ROW_BLOCK, k), lambda r: (r, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((r_pad, k), jnp.float32),
            jax.ShapeDtypeStruct((r_pad, k), jnp.int32),
        ],
        cost_estimate=c.as_pallas(),
        interpret=interpret,
    )(v, tie, pay)
    out_v = out_v[:rows]
    out_i = out_i[:rows]
    if not select_min:
        out_v = -out_v
    return out_v.astype(scores.dtype), out_i
