"""Pallas TPU kernels for the hot paths.

The reference spends its hand-written-kernel budget on exactly these spots
(SURVEY §2.2/§2.4/§2.8): k-selection (matrix/detail/select_radix.cuh,
select_warpsort.cuh), fused distance+reduction (distance/fused_l2_nn-inl.cuh,
spatial/knn/detail/fused_l2_knn-inl.cuh), and the IVF-PQ LUT scan
(neighbors/detail/ivf_pq_compute_similarity-inl.cuh).  On TPU the XLA
formulations of these are already strong, so each Pallas kernel here is an
*alternative* code path behind a dispatch flag — A/B measured by
``python -m raft_tpu.bench prims`` and enabled where it wins.

Dispatch: ``use_pallas()`` consults RAFT_TPU_PALLAS:
  - "0"    — never (pure XLA paths)
  - "1"    — always (interpret mode off-TPU; for tests)
  - "auto" — (default) on TPU backends only
"""

from __future__ import annotations

import jax

from raft_tpu.core import env as _env


def _platform() -> str:
    return jax.devices()[0].platform


def use_pallas() -> bool:
    mode = _env.env_str("RAFT_TPU_PALLAS", "auto")
    if mode == "0":
        return False
    if mode == "1":
        return True
    return _platform() == "tpu"


def interpret_mode() -> bool:
    """Pallas interpret=True off-TPU so kernels are testable on CPU
    (SURVEY §5: sanitizer analog — interpret mode is also the OOB guard)."""
    return _platform() != "tpu"


from raft_tpu.kernels.fused_knn import fused_l2_topk  # noqa: E402
from raft_tpu.kernels.fused_argmin import fused_l2_argmin  # noqa: E402
from raft_tpu.kernels.ivf_scan import ivf_scan_probe_major  # noqa: E402

__all__ = [
    "use_pallas",
    "interpret_mode",
    "fused_l2_topk",
    "fused_l2_argmin",
    "ivf_scan_probe_major",
]
