"""Pallas TPU kernels for the hot paths.

The reference spends its hand-written-kernel budget on exactly these spots
(SURVEY §2.2/§2.4/§2.8): k-selection (matrix/detail/select_radix.cuh,
select_warpsort.cuh), fused distance+reduction (distance/fused_l2_nn-inl.cuh,
spatial/knn/detail/fused_l2_knn-inl.cuh), and the IVF-PQ LUT scan
(neighbors/detail/ivf_pq_compute_similarity-inl.cuh).  On TPU the XLA
formulations of these are already strong, so each Pallas kernel here is an
*alternative* code path behind a dispatch flag — A/B measured by
``python -m raft_tpu.bench prims`` and enabled where it wins.

Dispatch: ``use_pallas()`` consults RAFT_TPU_PALLAS:
  - "0"    — never (pure XLA paths)
  - "1"    — always (interpret mode off-TPU; for tests)
  - "auto" — (default) on TPU backends only
"""

from __future__ import annotations

import threading

import jax

from raft_tpu.core import env as _env


def _platform() -> str:
    return jax.devices()[0].platform


def use_pallas() -> bool:
    mode = _env.env_str("RAFT_TPU_PALLAS", "auto")
    if mode == "0":
        return False
    if mode == "1":
        return True
    return _platform() == "tpu"


def select_k_enabled() -> bool:
    """Per-kernel revert knob under the master gate: the fused k-selection
    (kernels/select_k.py) routes from ops.matrix only when ``use_pallas()``
    AND this knob hold — so a select_k-specific regression can be rolled
    back without losing the scan kernels."""
    return _env.env_bool("RAFT_TPU_PALLAS_SELECT_K", True)


def cagra_fused_enabled() -> bool:
    """Per-kernel revert knob for the fused CAGRA hop
    (kernels/cagra_traverse.py), same contract as ``select_k_enabled``."""
    return _env.env_bool("RAFT_TPU_PALLAS_CAGRA", True)


# ---------------------------------------------------------------------------
# live kernel-path attribution
#
# The routing decisions above (and their per-leg twins inside
# neighbors/ivf_flat.py, neighbors/ivf_pq.py) happen in host Python on
# every search call, but the *outcome* — which leg actually ran — was
# visible only in frozen bench records.  The serve layer wants it per
# dispatch, so each routing branch stamps the leg it took into a
# thread-local and the batcher consumes the stamp right after the search
# callable returns (same thread, zero locks, zero clock calls).  Values
# are a tiny closed vocabulary: "pallas", "xla", "xla_filter_fallback"
# (the per-row-filter XLA leg), "sharded" (SPMD shard_map dispatch, where
# per-leg stamps would fire at trace time only), "sharded_graph" (the
# partitioned-graph CAGRA SPMD dispatch — separated from "sharded" so
# ledger hotspots and bench records can tell the traversal from the
# brute-refine control arm).

_kernel_path_tls = threading.local()


def stamp_kernel_path(path: str) -> None:
    """Record which kernel leg the current search call routed to."""
    _kernel_path_tls.value = path


def consume_kernel_path(default: str = "unknown") -> str:
    """Pop the stamp left by the last search on this thread (or
    ``default`` when the search ran elsewhere, e.g. on hedge threads)."""
    path = getattr(_kernel_path_tls, "value", None)
    _kernel_path_tls.value = None
    return path if path is not None else default


def interpret_mode() -> bool:
    """Pallas interpret=True off-TPU so kernels are testable on CPU
    (SURVEY §5: sanitizer analog — interpret mode is also the OOB guard)."""
    return _platform() != "tpu"


from raft_tpu.kernels.fused_knn import fused_l2_topk  # noqa: E402
from raft_tpu.kernels.fused_argmin import fused_l2_argmin  # noqa: E402
from raft_tpu.kernels.ivf_scan import ivf_scan_probe_major  # noqa: E402
from raft_tpu.kernels.select_k import select_k_pallas  # noqa: E402
from raft_tpu.kernels.cagra_traverse import cagra_fused_hop  # noqa: E402

__all__ = [
    "use_pallas",
    "select_k_enabled",
    "cagra_fused_enabled",
    "interpret_mode",
    "stamp_kernel_path",
    "consume_kernel_path",
    "fused_l2_topk",
    "fused_l2_argmin",
    "ivf_scan_probe_major",
    "select_k_pallas",
    "cagra_fused_hop",
]
