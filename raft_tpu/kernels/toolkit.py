"""Reusable Pallas/TPU kernel building blocks — the device-util toolkit.

The reference keeps a kernel toolkit under ``cpp/include/raft/util/``
(warp_primitives.cuh, bitonic_sort.cuh, pow2_utils.cuh, vectorized.cuh,
reduction.cuh — SURVEY §2.2) plus a shared-memory tiling-policy base for
pairwise kernels (``linalg/contractions.cuh``, §2.3). On TPU the warp/SM
machinery has no analog — the compiler owns vectorization — but the same
three needs recur in every hand-written kernel:

1. power-of-two / padding address math        (pow2_utils.cuh analog)
2. a tile-size policy fitting VMEM            (contractions.cuh analog)
3. an in-kernel running top-k maintenance     (bitonic warp-queue analog,
                                               select_warpsort.cuh idea)

They live here so each Pallas kernel composes them instead of re-deriving
them. Everything is a pure jnp function usable both inside ``pallas_call``
kernels and in plain XLA code (and therefore testable on CPU without
interpret mode).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp

# ---------------------------------------------------------------------------
# address math (ref: util/pow2_utils.cuh, util/integer_utils.hpp)

#: TPU native tile quanta: 8 sublanes × 128 lanes (f32).
SUBLANE = 8
LANE = 128


def cdiv(a: int, b: int) -> int:
    return -(-a // b)


def round_up(x: int, multiple: int) -> int:
    return cdiv(x, multiple) * multiple


def next_pow2(x: int) -> int:
    return 1 << max(0, int(x - 1).bit_length())


def pad_dim(x: jax.Array, axis: int, multiple: int, fill=0) -> jax.Array:
    """Pad one axis up to a multiple (the kernel-edge guard the reference
    handles with per-thread bounds checks; on TPU padding is the idiom)."""
    pad = (-x.shape[axis]) % multiple
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=fill)


# ---------------------------------------------------------------------------
# tile policy (ref: linalg/contractions.cuh Policy4x4 etc.)


@dataclass(frozen=True)
class TilePolicy:
    """Tile shape for an [m, d] × [n, d] pairwise contraction kernel."""

    tile_m: int
    tile_n: int
    grid: Tuple[int, int]
    vmem_bytes: int  # estimated per-step VMEM footprint


def choose_tile_policy(
    m: int,
    n: int,
    d: int,
    *,
    itemsize: int = 4,
    extra_cols: int = 0,
    vmem_budget: int = 8 * 1024 * 1024,
    max_tile_m: int = 512,
    max_tile_n: int = 1024,
) -> TilePolicy:
    """Pick (tile_m, tile_n) so both operand tiles + the score tile fit the
    VMEM budget (the reference solves the same constraint against shared
    memory with hard-coded Policy types, contractions.cuh; here it's a
    closed-form shrink from the largest MXU-aligned tiles).

    ``extra_cols`` accounts for per-kernel extras held per tile_m row
    (e.g. a running top-k of width k_pad).
    """
    d_pad = round_up(max(d, 1), LANE)
    tile_m = min(max_tile_m, round_up(max(m, 1), SUBLANE))
    tile_n = min(max_tile_n, round_up(max(n, 1), LANE))

    def footprint(tm: int, tn: int) -> int:
        # q tile + x tile + f32 score tile + extras
        return (
            (tm + tn) * d_pad * itemsize
            + tm * tn * 4
            + tm * extra_cols * 8
        )

    # halve-then-re-round so tiles always stay on the native quantum (a
    # non-power-of-two start like 160 must not shrink below/for off LANE)
    while footprint(tile_m, tile_n) > vmem_budget and tile_n > LANE:
        tile_n = max(LANE, round_up(tile_n // 2, LANE))
    while footprint(tile_m, tile_n) > vmem_budget and tile_m > SUBLANE:
        tile_m = max(SUBLANE, round_up(tile_m // 2, SUBLANE))
    return TilePolicy(
        tile_m,
        tile_n,
        (cdiv(m, tile_m), cdiv(n, tile_n)),
        footprint(tile_m, tile_n),
    )


# ---------------------------------------------------------------------------
# in-kernel running top-k (ref idea: matrix/detail/select_warpsort.cuh warp
# queues — fold a fresh candidate tile into a resident sorted queue)


def fold_topk(
    run_v: jax.Array,   # [rows, k_pad] current best values (ascending-ish)
    run_i: jax.Array,   # [rows, k_pad] their indices
    cand_v: jax.Array,  # [rows, c] new candidate values
    cand_i: jax.Array,  # [rows, c] their indices
    k: int,
    *,
    worst: float = float("inf"),
) -> Tuple[jax.Array, jax.Array]:
    """Fold a candidate tile into a resident top-k (select-min): k rounds of
    masked min-extraction over the concatenated pool. O(k·(k_pad+c)) VPU work
    with no sort network — the right trade for the k ≤ 128 regime the fused
    kernels serve. Returns ([rows, k_pad] vals, idx) with slots ≥ k = worst.
    """
    rows, k_pad = run_v.shape
    pool_v = jnp.concatenate([run_v, cand_v], axis=1)
    pool_i = jnp.concatenate([run_i, cand_i], axis=1)
    n_pool = pool_v.shape[1]
    pos = jax.lax.broadcasted_iota(jnp.int32, (rows, n_pool), 1)

    def extract(t, carry):
        pool, out_v, out_i = carry
        m = jnp.min(pool, axis=1)
        first = jnp.min(jnp.where(pool == m[:, None], pos, n_pool), axis=1)
        onehot = pos == first[:, None]
        sel_i = jnp.sum(jnp.where(onehot, pool_i, 0), axis=1)
        hole = jax.lax.broadcasted_iota(jnp.int32, (rows, k_pad), 1) == t
        out_v = jnp.where(hole, m[:, None], out_v)
        out_i = jnp.where(hole, sel_i[:, None], out_i)
        return jnp.where(onehot, worst, pool), out_v, out_i

    out_v0 = jnp.full((rows, k_pad), worst, pool_v.dtype)
    out_i0 = jnp.zeros((rows, k_pad), pool_i.dtype)
    _, out_v, out_i = jax.lax.fori_loop(
        0, k, extract, (pool_v, out_v0, out_i0)
    )
    return out_v, out_i


def quantize_queries_i8(q: jax.Array):
    """Per-row symmetric int8 quantization of query rows [.., rot] →
    (q_i8 same shape, scale [.., 1] f32 with a 1e-12 floor). THE one copy
    of the quantized-query recipe — the Pallas int8 scan leg and both XLA
    int8 score paths must stay numerically identical for the kernel-vs-XLA
    parity tests to hold (pure jnp, Pallas-safe)."""
    sq = jnp.maximum(
        jnp.max(jnp.abs(q), axis=-1, keepdims=True) / 127.0, 1e-12
    )
    q_i8 = jnp.clip(jnp.round(q / sq), -127, 127).astype(jnp.int8)
    return q_i8, sq


def int8_scored_ip(qr: jax.Array, dec_i8: jax.Array, dims, scan_scale):
    """q·y inner products against an int8 scan cache: per-row symmetric
    quantization of ``qr`` (:func:`quantize_queries_i8`), int8×int8 MXU
    dot with the given ``dot_general`` dimension numbers, f32 rescale by
    (per-row scale × global ``scan_scale``). THE one copy of the XLA
    int8-score recipe — the single-device query/probe-major scans and the
    sharded scan all call this so they stay numerically identical to each
    other and to the Pallas kernel's quantized leg."""
    from jax import lax

    q_i8, sq = quantize_queries_i8(qr)
    ip_i32 = lax.dot_general(
        q_i8, dec_i8, dims, preferred_element_type=jnp.int32
    )
    # sq is qr.shape[:-1] + (1,); right-pad axes so it broadcasts over the
    # ip result's trailing (…, cap) dims
    extra = ip_i32.ndim - sq.ndim
    if extra:
        sq = sq.reshape(sq.shape[:-1] + (1,) * (extra + 1))
    return ip_i32.astype(jnp.float32) * (sq * scan_scale)


def col_ids_tile(rows: int, tile_n: int, col_base) -> jax.Array:
    """Global column indices of a [rows, tile_n] tile starting at col_base
    (the vectorized-iota every tiled kernel needs)."""
    return col_base + jax.lax.broadcasted_iota(jnp.int32, (rows, tile_n), 1)
