"""Combinatorial solvers (ref: cpp/include/raft/solver/)."""

from raft_tpu.solver.linear_assignment import linear_assignment

__all__ = ["linear_assignment"]
