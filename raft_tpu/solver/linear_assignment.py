"""Linear assignment problem (LAP).

Reference: ``solver/linear_assignment.cuh`` (LinearAssignmentProblem — a GPU
Hungarian/augmenting implementation, legacy ``lap/lap.cuh``, SURVEY §2.12).

TPU re-design: the Hungarian algorithm's augmenting paths are sequential and
pointer-chasing — hostile to XLA. The auction algorithm (Bertsekas) solves
the same problem with bulk-synchronous rounds: every unassigned row bids for
its best column (one masked row-max + second-max), every column takes its
best bid (one segment-max), prices rise monotonically. With ε-scaling the
result converges to the optimal assignment; each round is pure vectorized
VPU work inside a ``lax.while_loop``.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_NEG = -jnp.inf


@functools.partial(jax.jit, static_argnames=("maximize",))
def _auction(cost: jax.Array, maximize: bool, eps_final: jax.Array):
    n = cost.shape[0]
    a = cost if maximize else -cost           # benefit matrix
    scale = jnp.maximum(jnp.max(jnp.abs(a)), 1e-12)

    def phase(carry):
        eps, prices, owner, person_of = carry
        # reset assignment each phase (standard ε-scaling restarts)
        owner = jnp.full((n,), -1, jnp.int32)       # object → person
        person_of = jnp.full((n,), -1, jnp.int32)   # person → object

        def round_cond(state):
            owner, person_of, prices, it = state
            return (jnp.any(person_of < 0)) & (it < 8 * n * n + 64)

        def round_body(state):
            owner, person_of, prices, it = state
            unassigned = person_of < 0
            vals = a - prices[None, :]
            j1 = jnp.argmax(vals, axis=1)
            v1 = jnp.take_along_axis(vals, j1[:, None], axis=1)[:, 0]
            masked = vals.at[jnp.arange(n), j1].set(_NEG)
            v2 = jnp.max(masked, axis=1)
            v2 = jnp.where(jnp.isfinite(v2), v2, v1 - 1.0)
            bid = prices[j1] + (v1 - v2) + eps
            obj = jnp.where(unassigned, j1, n)
            best_bid = jax.ops.segment_max(
                jnp.where(unassigned, bid, _NEG), obj, num_segments=n + 1
            )[:n]
            is_best = unassigned & (best_bid[j1] == bid)
            winner = jax.ops.segment_min(
                jnp.where(is_best, jnp.arange(n, dtype=jnp.int32),
                          jnp.iinfo(jnp.int32).max),
                obj, num_segments=n + 1,
            )[:n]
            took = winner < jnp.iinfo(jnp.int32).max
            prices = jnp.where(took, best_bid, prices)
            # displaced owners lose their object
            displaced = jnp.where(took, owner, -1)           # [n] person ids
            person_of = person_of.at[
                jnp.where(displaced >= 0, displaced, n)
            ].set(-1, mode="drop")
            # winners gain their object
            wsafe = jnp.where(took, winner, n)
            person_of = person_of.at[wsafe].set(
                jnp.where(took, jnp.arange(n, dtype=jnp.int32), -1), mode="drop"
            )
            owner = jnp.where(took, winner, owner)
            return owner, person_of, prices, it + 1

        owner, person_of, prices, _ = lax.while_loop(
            round_cond, round_body,
            (owner, person_of, prices, jnp.zeros((), jnp.int32)),
        )
        return eps / 4.0, prices, owner, person_of

    def scaling_cond(carry):
        eps, prices, owner, person_of = carry
        return eps >= eps_final

    eps0 = jnp.maximum(scale / 4.0, eps_final)
    init = (
        eps0,
        jnp.zeros((n,), a.dtype),
        jnp.full((n,), -1, jnp.int32),
        jnp.full((n,), -1, jnp.int32),
    )
    _, prices, owner, person_of = lax.while_loop(scaling_cond, phase, init)
    return person_of


def linear_assignment(
    cost: jax.Array, *, maximize: bool = False, eps: float = 0.0
) -> Tuple[jax.Array, jax.Array]:
    """Solve the n×n assignment problem.

    Returns (col_of_row [n] int32, total_cost). Optimal within n·ε of the
    true optimum; the default ε targets exactness for well-separated float
    costs (ref: solver/linear_assignment.cuh LinearAssignmentProblem::solve)."""
    cost = jnp.asarray(cost, jnp.float32)
    n, m = cost.shape
    if n != m:
        raise ValueError(f"cost matrix must be square, got {cost.shape}")
    scale = float(jnp.max(jnp.abs(cost))) or 1.0
    eps_final = jnp.asarray(eps or max(1e-7, 1e-4 * scale / max(n, 1)), jnp.float32)
    person_of = _auction(cost, maximize, eps_final)
    if bool(jnp.any(person_of < 0)):
        # the per-phase round cap tripped before convergence (near-degenerate
        # costs); a silent partial assignment would corrupt the total
        raise RuntimeError(
            "auction did not converge — retry with a larger eps "
            "(accuracy/speed trade-off, ref Bertsekas ε-scaling)"
        )
    total = jnp.sum(
        jnp.take_along_axis(cost, person_of[:, None].astype(jnp.int32), axis=1)
    )
    return person_of, total
