"""The shared project model every checker runs against.

One pass parses the package with :mod:`ast` and builds:

* a **module index** (dotted name → parsed tree + source + per-line
  suppressions + import alias map),
* a **function index** (dotted qualname → def node, class, module) over
  top-level functions and methods,
* a **call graph** with conservative name resolution — ``self.meth()``
  within a class, bare names to same-module or imported functions,
  ``mod.fn()`` through project-module imports.  Unresolvable dynamic
  calls simply contribute no edge (checkers stay sound w.r.t. what they
  claim, not complete),
* a **lock inventory**: ``self._x = threading.Lock/RLock/Condition/
  Semaphore`` attributes per class and module-level lock assignments.

Checkers consume this read-only and emit findings through
:meth:`Project.finding`, which applies per-line suppression comments
(``# raft-tpu: ignore[RULE]`` — several rules comma-separated; the
comment anywhere on the flagged node's physical lines suppresses it).

Everything here is stdlib-only and never imports the modules it
analyzes — no jax tracing, no device, so the tier-1 test and the CLI
stay CPU-cheap (the unavoidable cost is ``raft_tpu/__init__`` running
on package import).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from raft_tpu.analysis.findings import Finding

_SUPPRESS_RE = re.compile(r"#\s*raft-tpu:\s*ignore\[([A-Z0-9_,\s]+)\]")

#: threading constructors whose instances count as locks
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class FunctionInfo:
    """One top-level function or method (nested defs stay inside)."""

    qualname: str                    # "pkg.mod.Class.meth" / "pkg.mod.fn"
    module: "ModuleInfo"
    node: ast.AST                    # FunctionDef | AsyncFunctionDef
    class_name: Optional[str] = None
    calls: Set[str] = field(default_factory=set)  # resolved callee qualnames

    @property
    def name(self) -> str:
        return self.node.name


@dataclass
class ClassInfo:
    qualname: str
    module: "ModuleInfo"
    node: ast.ClassDef
    lock_attrs: Dict[str, str] = field(default_factory=dict)  # attr → ctor
    #: ``self._cond = Condition(self._lock)`` makes _cond an alias of
    #: _lock — acquiring either takes the same underlying lock
    lock_aliases: Dict[str, str] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    name: str                        # dotted, package-rooted: "pkg.sub.mod"
    path: str                        # relative to the scan root's parent
    tree: ast.Module
    source: str
    suppressions: Dict[int, Set[str]]          # line → rules ignored there
    imports: Dict[str, str] = field(default_factory=dict)  # alias → dotted
    module_locks: Dict[str, str] = field(default_factory=dict)  # name → ctor

    def lines(self, node: ast.AST) -> Iterable[int]:
        start = getattr(node, "lineno", None)
        if start is None:
            return ()
        return range(start, (getattr(node, "end_lineno", None) or start) + 1)

    def is_suppressed(self, rule: str, node: ast.AST) -> bool:
        for line in self.lines(node):
            if rule in self.suppressions.get(line, ()):
                return True
        return False


def _scan_suppressions(source: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[lineno] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def _scan_imports(tree: ast.Module) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
                if a.asname is None and "." in a.name:
                    # "import a.b.c" binds "a" but makes a.b.c importable;
                    # remember the full path under its head for resolution
                    aliases.setdefault(a.name, a.name)
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                if a.name != "*":
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


class Project:
    """Parsed view of one package directory (``raft_tpu`` or a fixture)."""

    def __init__(self, root: str, readme: Optional[str] = None):
        self.root = os.path.abspath(root)
        self.package = os.path.basename(self.root)
        self.base = os.path.dirname(self.root)
        #: repo-root README to reconcile the env table against (ENVREG);
        #: autodetected next to the package when not given
        if readme is None:
            candidate = os.path.join(self.base, "README.md")
            readme = candidate if os.path.exists(candidate) else None
        self.readme = readme
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self._parse_tree()
        self._index_defs()
        self._resolve_calls()

    # -- construction --------------------------------------------------------
    def _parse_tree(self) -> None:
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = sorted(
                d for d in dirnames
                if d != "__pycache__" and not d.startswith(".")
            )
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                rel = os.path.relpath(path, self.base)
                parts = os.path.relpath(path, self.root)[:-3].split(os.sep)
                if parts[-1] == "__init__":
                    parts = parts[:-1]
                name = ".".join([self.package] + [p for p in parts if p])
                with open(path, encoding="utf-8") as f:
                    source = f.read()
                tree = ast.parse(source, filename=rel)
                self.modules[name] = ModuleInfo(
                    name=name,
                    path=rel,
                    tree=tree,
                    source=source,
                    suppressions=_scan_suppressions(source),
                    imports=_scan_imports(tree),
                )

    def _index_defs(self) -> None:
        for mod in self.modules.values():
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = f"{mod.name}.{node.name}"
                    self.functions[q] = FunctionInfo(q, mod, node)
                elif isinstance(node, ast.ClassDef):
                    cq = f"{mod.name}.{node.name}"
                    cls = ClassInfo(cq, mod, node)
                    self.classes[cq] = cls
                    for item in node.body:
                        if isinstance(
                            item, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            fq = f"{cq}.{item.name}"
                            self.functions[fq] = FunctionInfo(
                                fq, mod, item, class_name=node.name
                            )
                    self._collect_lock_attrs(cls)
                elif isinstance(node, ast.Assign):
                    self._collect_module_lock(mod, node)

    def _lock_ctor(self, mod: ModuleInfo, call: ast.AST) -> Optional[str]:
        """``"Lock"``/``"RLock"``/... when ``call`` constructs one."""
        if not isinstance(call, ast.Call):
            return None
        name = dotted(call.func)
        if name is None:
            return None
        head, _, tail = name.rpartition(".")
        ctor = tail or name
        if ctor not in _LOCK_CTORS:
            return None
        if head:
            return ctor if mod.imports.get(head, head) == "threading" else None
        return (
            ctor if mod.imports.get(ctor, "") == f"threading.{ctor}" else None
        )

    def _collect_lock_attrs(self, cls: ClassInfo) -> None:
        for node in ast.walk(cls.node):
            if not isinstance(node, ast.Assign):
                continue
            ctor = self._lock_ctor(cls.module, node.value)
            if ctor is None:
                # Condition(self._lock) wrapping an existing lock is the
                # same lock; plain aliases are not re-counted
                continue
            alias_of = None
            if ctor == "Condition":
                if node.value.args:
                    wrapped = node.value.args[0]
                    if (
                        isinstance(wrapped, ast.Attribute)
                        and isinstance(wrapped.value, ast.Name)
                        and wrapped.value.id == "self"
                    ):
                        alias_of = wrapped.attr
                else:
                    ctor = "RLock"  # bare Condition() is backed by an RLock
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    if alias_of is not None:
                        cls.lock_aliases[tgt.attr] = alias_of
                    else:
                        cls.lock_attrs[tgt.attr] = ctor

    def _collect_module_lock(self, mod: ModuleInfo, node: ast.Assign) -> None:
        ctor = self._lock_ctor(mod, node.value)
        if ctor is None:
            return
        if ctor == "Condition" and not node.value.args:
            ctor = "RLock"
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                mod.module_locks[tgt.id] = ctor

    # -- call-graph resolution -----------------------------------------------
    def _project_module(self, dotted_name: str) -> Optional[str]:
        """Map an imported dotted name onto a scanned module, if any."""
        if dotted_name in self.modules:
            return dotted_name
        return None

    def _resolve_calls(self) -> None:
        for fn in self.functions.values():
            mod = fn.module
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = self._resolve_callee(fn, mod, node.func)
                if callee is not None:
                    fn.calls.add(callee)

    def _resolve_callee(
        self, fn: FunctionInfo, mod: ModuleInfo, func: ast.AST
    ) -> Optional[str]:
        # self.meth() → method on the same class
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
            and fn.class_name is not None
        ):
            q = f"{mod.name}.{fn.class_name}.{func.attr}"
            return q if q in self.functions else None
        name = dotted(func)
        if name is None:
            return None
        if "." not in name:
            # bare call: same-module function, else from-import
            q = f"{mod.name}.{name}"
            if q in self.functions:
                return q
            target = mod.imports.get(name)
            if target and target in self.functions:
                return target
            return None
        head, _, tail = name.rpartition(".")
        target_mod = self._project_module(mod.imports.get(head, head))
        if target_mod is not None:
            q = f"{target_mod}.{tail}"
            return q if q in self.functions else None
        return None

    # -- queries -------------------------------------------------------------
    def functions_matching(self, suffix: str) -> List[FunctionInfo]:
        """Functions whose qualname ends with ``suffix`` (dot-anchored)."""
        out = []
        for q, fn in self.functions.items():
            if q == suffix or q.endswith("." + suffix):
                out.append(fn)
        return out

    def classes_matching(self, suffix: str) -> List[ClassInfo]:
        out = []
        for q, cls in self.classes.items():
            if q == suffix or q.endswith("." + suffix):
                out.append(cls)
        return out

    def modules_matching(self, suffix: str) -> List[ModuleInfo]:
        out = []
        for name, mod in self.modules.items():
            if name == suffix or name.endswith("." + suffix):
                out.append(mod)
        return out

    def reachable(self, roots: Sequence[FunctionInfo]) -> List[FunctionInfo]:
        """Transitive closure over resolved call edges, roots included."""
        seen: Dict[str, FunctionInfo] = {}
        stack = list(roots)
        while stack:
            fn = stack.pop()
            if fn.qualname in seen:
                continue
            seen[fn.qualname] = fn
            for callee in fn.calls:
                nxt = self.functions.get(callee)
                if nxt is not None and nxt.qualname not in seen:
                    stack.append(nxt)
        return list(seen.values())

    # -- finding emission ----------------------------------------------------
    def finding(
        self,
        rule: str,
        mod: ModuleInfo,
        node: ast.AST,
        symbol: str,
        message: str,
        suppressed_sink: Optional[List[Finding]] = None,
    ) -> Optional[Finding]:
        """Build a Finding unless a suppression comment covers ``node``."""
        f = Finding(
            rule=rule,
            path=mod.path,
            line=getattr(node, "lineno", 0) or 0,
            symbol=symbol,
            message=message,
        )
        if mod.is_suppressed(rule, node):
            if suppressed_sink is not None:
                suppressed_sink.append(f)
            return None
        return f


# -- shared AST helpers used by several checkers ----------------------------

def resolves_to(mod: ModuleInfo, node: ast.AST, full: str) -> bool:
    """Whether a Name/Attribute chain denotes ``full`` under the module's
    import aliases (``jnp.asarray`` → ``jax.numpy.asarray``, ...)."""
    name = dotted(node)
    if name is None:
        return False
    head, _, rest = name.partition(".")
    resolved = mod.imports.get(head, head)
    return (resolved + ("." + rest if rest else "")) == full


def call_name(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    """The import-resolved dotted name of a call target, else None."""
    name = dotted(call.func)
    if name is None:
        return None
    head, _, rest = name.partition(".")
    resolved = mod.imports.get(head, head)
    return resolved + ("." + rest if rest else "")


def walk_scope(node: ast.AST) -> Iterable[ast.AST]:
    """``ast.walk`` that does not descend into nested def/class bodies."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if isinstance(
            child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                    ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(child))
