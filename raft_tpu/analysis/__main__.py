"""CLI for the static invariant checkers.

::

    python -m raft_tpu.analysis                      # check the package
    python -m raft_tpu.analysis --rules HOSTSYNC,LOCKORDER
    python -m raft_tpu.analysis --baseline analysis_baseline.json
    python -m raft_tpu.analysis --write-baseline analysis_baseline.json
    python -m raft_tpu.analysis --root path/to/pkg --json

Exit status 0 when every finding is suppressed or baselined, 1
otherwise (2 on usage errors) — cheap to wire into any CI gate.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from raft_tpu.analysis import (
    RULES,
    load_baseline,
    run_analysis,
    write_baseline,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m raft_tpu.analysis", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--root", default=None,
                    help="package directory to scan (default: raft_tpu)")
    ap.add_argument("--readme", default=None,
                    help="README to reconcile the env table against "
                         "(default: autodetected next to the package)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON; findings whose IDs appear there "
                         "are reported but do not fail the run")
    ap.add_argument("--write-baseline", default=None, metavar="PATH",
                    help="record the current findings as the accepted "
                         "baseline and exit 0")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as a JSON document")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULES():
            print(r)
        return 0

    rules = None
    if args.rules:
        rules = [r.strip().upper() for r in args.rules.split(",") if r.strip()]

    t0 = time.perf_counter()
    try:
        result = run_analysis(root=args.root, rules=rules,
                              readme=args.readme)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    elapsed = time.perf_counter() - t0

    findings = result.sorted_findings()

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print(f"wrote {len(findings)} finding IDs to {args.write_baseline}")
        return 0

    baseline = set()
    if args.baseline:
        baseline = load_baseline(args.baseline)
    fresh = [f for f in findings if f.id not in baseline]
    known = [f for f in findings if f.id in baseline]

    if args.json:
        print(json.dumps({
            "elapsed_s": round(elapsed, 3),
            "stats": result.stats,
            "findings": [f.to_dict() for f in fresh],
            "baselined": [f.to_dict() for f in known],
            "suppressed": len(result.suppressed),
        }, indent=2))
    else:
        for f in fresh:
            print(f.render())
        for f in known:
            print(f"{f.render()}  [baselined]")
        print(
            f"raft_tpu.analysis: {len(fresh)} finding(s)"
            f"{f', {len(known)} baselined' if known else ''}"
            f", {len(result.suppressed)} suppressed, "
            f"{result.stats.get('modules', 0)} modules in {elapsed:.2f}s"
        )
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
