"""Findings: the unit of output every checker produces.

A finding's identity is content-addressed — rule, file, symbol and
message, but **not** the line number — so IDs survive unrelated edits
to the same file (a baseline pinned to line numbers would churn on
every reflow).  Two findings with the same rule/file/symbol/message
are the same finding.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Set

BASELINE_VERSION = 1


def _digest(rule: str, path: str, symbol: str, message: str) -> str:
    h = hashlib.sha1(f"{rule}|{path}|{symbol}|{message}".encode())
    return h.hexdigest()[:8]


@dataclass(frozen=True)
class Finding:
    """One invariant violation at a concrete site."""

    rule: str      # RECOMPILE / HOSTSYNC / LOCKORDER / ENVREG / TRACED
    path: str      # file path relative to the scan root's parent
    line: int      # 1-based; for display only, not part of the ID
    symbol: str    # dotted qualname (or var name) the finding anchors to
    message: str
    id: str = field(init=False)

    def __post_init__(self):
        object.__setattr__(
            self, "id", _digest(self.rule, self.path, self.symbol, self.message)
        )

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.rule}[{self.id}] "
            f"{self.symbol}: {self.message}"
        )

    def to_dict(self) -> Dict[str, object]:
        return {
            "id": self.id,
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
        }


def load_baseline(path: str) -> Set[str]:
    """The set of accepted finding IDs recorded in a baseline file."""
    with open(path) as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}, "
            f"expected {BASELINE_VERSION}"
        )
    return {str(i) for i in data.get("ids", [])}


def write_baseline(path: str, findings: List[Finding]) -> None:
    """Record the current unsuppressed findings as the accepted set."""
    data = {
        "version": BASELINE_VERSION,
        "ids": sorted(f.id for f in findings),
        # context only — the IDs above are what filtering reads
        "findings": [f.to_dict() for f in sorted(
            findings, key=lambda f: (f.path, f.line, f.rule))],
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")
