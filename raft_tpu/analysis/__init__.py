"""Static invariant checking for the raft_tpu codebase.

The serving stack rests on invariants that used to be enforced only
dynamically (the zero-recompile contract via a bench-time compile
counter, lock discipline via soak tests) or by ad-hoc test scripts.
This package is the static end of those contracts: one :mod:`ast` pass
builds a shared project model (:mod:`raft_tpu.analysis.model`) and
pluggable checkers (:mod:`raft_tpu.analysis.checkers`) walk it:

========== ==============================================================
RECOMPILE  jit-traced code branching on traced values / mutable closures
HOSTSYNC   device→host syncs reachable from the serving hot paths
LOCKORDER  lock-acquisition cycles + unguarded writes to guarded attrs
ENVREG     RAFT_TPU_* knobs vs the core/env.py registry and README table
TRACED     span coverage of the exported + serve API surface
========== ==============================================================

CLI::

    python -m raft_tpu.analysis [--baseline analysis_baseline.json]

exits nonzero on any unsuppressed, unbaselined finding.  Suppress a
deliberate site inline with ``# raft-tpu: ignore[RULE]`` (comma-
separate several rules) plus a reason.  See ``docs/analysis.md``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from raft_tpu.analysis.findings import (
    Finding,
    load_baseline,
    write_baseline,
)
from raft_tpu.analysis.model import Project

__all__ = [
    "AnalysisResult",
    "Finding",
    "Project",
    "run_analysis",
    "load_baseline",
    "write_baseline",
    "RULES",
]


def _default_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@dataclass
class AnalysisResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    stats: Dict[str, int] = field(default_factory=dict)

    def sorted_findings(self) -> List[Finding]:
        return sorted(
            self.findings, key=lambda f: (f.path, f.line, f.rule, f.id)
        )


def RULES() -> List[str]:
    from raft_tpu.analysis.checkers import CHECKERS

    return sorted(CHECKERS)


def run_analysis(
    root: Optional[str] = None,
    rules: Optional[Sequence[str]] = None,
    readme: Optional[str] = None,
) -> AnalysisResult:
    """Parse ``root`` (default: the installed raft_tpu package) and run
    the selected checkers (default: all) over it."""
    from raft_tpu.analysis.checkers import CHECKERS

    project = Project(root or _default_root(), readme=readme)
    selected = list(rules) if rules else sorted(CHECKERS)
    unknown = [r for r in selected if r not in CHECKERS]
    if unknown:
        raise ValueError(
            f"unknown rules {unknown}; available: {sorted(CHECKERS)}"
        )
    result = AnalysisResult()
    result.stats["modules"] = len(project.modules)
    result.stats["functions"] = len(project.functions)
    for rule in selected:
        CHECKERS[rule](project, result)
    return result
