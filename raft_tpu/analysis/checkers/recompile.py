"""RECOMPILE — jit-traced code that branches on traced values.

The zero-recompile contract (pow2 bucket ladder + warmup; enforced
dynamically by the bench-time compile counter) has a static shadow:
inside a jit-traced function, Python control flow on the *value* of a
traced argument either fails to trace or — worse — silently
specializes, recompiling per distinct value.  ``.shape``/``.ndim``/
``.dtype``/``len()`` are static under trace and fine to branch on;
``static_argnums``/``static_argnames``/``functools.partial``-bound
parameters are Python values by construction.  One extra contract rides
on top: a typed search-effort knob (:data:`EFFORT_KNOB_NAMES`) may only
be a static jit argument on the *private* warmed-variant layer
(underscore-prefixed defs — the executables the serving warmup ladder
precompiles per (bucket, level)).  On any public jit entry a static
knob bypasses the ladder entirely: the autotuner actuates knob values
per tick, and each level change would recompile.

Detected jit entries: ``@jax.jit`` / ``@partial(jax.jit, ...)``
decorated defs, and local/module functions (or lambdas / partials)
passed to an inline ``jax.jit(...)`` call.  Within them the checker
taints non-static parameters, propagates through simple assignments,
and flags ``if``/``while``/ternary/``assert`` tests, ``int()/float()/
bool()`` concretizations and ``for`` iteration over tainted values.
Inline-jitted closures are additionally checked for captured mutable
Python containers (list/dict/set built in the enclosing scope): those
are not hashable jit-cache keys and mutating them between calls skews
tracing.

The ragged serving path gets the same treatment without a jit
decorator: the :data:`DESCRIPTOR_ENTRIES` functions run inside the
batcher's already-compiled dispatch, where the per-request ``row_k``
descriptor column is traced *data* — Python control flow on its value
would re-specialize per batch mix, resurrecting the per-k executable
lattice ragged mode exists to retire.  (``row_fid`` is exempt: its
host-side table gather is the documented design.)
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from raft_tpu.analysis.model import (
    ModuleInfo,
    Project,
    call_name,
    dotted,
    walk_scope,
)

#: attribute reads that are static under trace — they launder taint
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes", "sharding",
                 "weak_type", "aval"}

#: calls whose result is static regardless of traced operands
_STATIC_CALLS = {"len", "isinstance", "hasattr", "getattr", "type"}

_MUTABLE_CTORS = {"list", "dict", "set", "bytearray"}

#: ragged descriptor-path functions (qualname suffix → descriptor params
#: held to jit discipline even though the defs carry no @jax.jit — they
#: execute inside the batcher's compiled dispatch).  Only the listed
#: params are tainted: everything else on these signatures is either a
#: plain array or deliberately host-side.
DESCRIPTOR_ENTRIES = {
    "serve.ragged.RaggedSearcher.__call__": ("row_k",),
    "serve.mutation.MutableIndex.search": ("row_k",),
    "ops.matrix.select_k": ("row_k",),
    "ops.matrix.mask_row_k": ("row_k",),
}

#: typed search-effort knob names (mirrors
#: ``neighbors.effort.EFFORT_KNOBS`` — the checker stays stdlib-only, a
#: tier-1 test pins the two sets in sync).  Effort values are host
#: operands that select among *warmed* executables; marking one static
#: (``static_argnums``/``static_argnames``/partial-bound) recompiles per
#: autotune level and defeats zero-recompile effort actuation.
EFFORT_KNOB_NAMES = frozenset(
    {"n_probes", "refine_ratio", "lut_dtype", "itopk_size", "search_width"}
)


def check(project: Project, result) -> None:
    n_entries = 0
    for mod in project.modules.values():
        entries = list(_jit_entries(project, mod))
        n_entries += len(entries)
        for node, static_idx, static_names, enclosing in entries:
            _check_effort_static(project, mod, node, static_idx,
                                 static_names, result)
            _check_entry(project, mod, node, static_idx, static_names,
                         result)
            if enclosing is not None and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                _check_closure(project, mod, node, enclosing, result)
    result.stats["recompile_jit_entries"] = n_entries
    _check_descriptor_entries(project, result)


def _check_descriptor_entries(project: Project, result) -> None:
    n_desc = 0
    for suffix, cols in sorted(DESCRIPTOR_ENTRIES.items()):
        for fn in project.functions.values():
            if not fn.qualname.endswith(suffix):
                continue
            n_desc += 1
            static = {p for p in _params(fn.node) if p not in cols}
            _check_entry(project, fn.module, fn.node, set(), static,
                         result)
    result.stats["recompile_descriptor_entries"] = n_desc


def _check_effort_static(project, mod, node, static_idx, static_names,
                         result) -> None:
    """Effort knobs must ride as operands on the public surface — a
    static knob keys the jit cache, so every autotune level change
    recompiles.  Private (underscore-prefixed) defs are exempt: they are
    the warmed-variant layer whose per-knob executables the serving
    warmup ladder precompiles deliberately."""
    symbol = getattr(node, "name", "<lambda>")
    if symbol.startswith("_"):
        return
    a = node.args
    positional = [p.arg for p in (a.posonlyargs + a.args)]
    offset = 1 if positional[:1] in (["self"], ["cls"]) else 0
    static: Set[str] = set(static_names)
    for i in static_idx:
        j = i + offset
        if 0 <= j < len(positional):
            static.add(positional[j])
    bad = sorted(static & EFFORT_KNOB_NAMES)
    if not bad:
        return
    _emit(project, mod, node, f"{mod.name}.{symbol}", result,
          f"effort knob(s) {', '.join(repr(b) for b in bad)} marked "
          "static under jit on a public entry — effort values are "
          "operands selecting among warmed executables; a static knob "
          "here recompiles per autotune level (private warmed variants "
          "are the one exempt layer)")


# -- jit-entry discovery ----------------------------------------------------

def _is_jit(mod: ModuleInfo, node: ast.AST) -> bool:
    name = dotted(node)
    if name is None:
        return False
    head, _, rest = name.partition(".")
    resolved = mod.imports.get(head, head) + ("." + rest if rest else "")
    return resolved == "jax.jit"


def _static_kwargs(
    keywords: Iterable[ast.keyword],
) -> Tuple[Set[int], Set[str]]:
    idx: Set[int] = set()
    names: Set[str] = set()
    for kw in keywords:
        if kw.arg == "static_argnums":
            for v in _int_values(kw.value):
                idx.add(v)
        elif kw.arg == "static_argnames":
            for v in _str_values(kw.value):
                names.add(v)
    return idx, names


def _int_values(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            out.extend(_int_values(e))
        return out
    return []


def _str_values(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            out.extend(_str_values(e))
        return out
    return []


def _jit_entries(project: Project, mod: ModuleInfo):
    """Yield (def_or_lambda, static_idx, static_names, enclosing_fn)."""
    # decorated defs (top-level and methods)
    for fn in project.functions.values():
        if fn.module is not mod:
            continue
        for dec in fn.node.decorator_list:
            entry = _decorator_jit(mod, dec)
            if entry is not None:
                yield (fn.node, *entry, None)
                break

    # inline jax.jit(X, ...) calls, resolving X in its lexical scope
    for scope, encl in _scopes(mod):
        local_defs = {
            n.name: n
            for n in walk_scope(scope)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for node in walk_scope(scope):
            if not (isinstance(node, ast.Call) and _is_jit(mod, node.func)
                    and node.args):
                continue
            static_idx, static_names = _static_kwargs(node.keywords)
            target = node.args[0]
            if isinstance(target, ast.Lambda):
                yield target, static_idx, static_names, encl
            elif isinstance(target, ast.Name):
                d = local_defs.get(target.id) or _module_def(mod, target.id)
                if d is not None:
                    yield d, static_idx, static_names, (
                        encl if target.id in local_defs else None
                    )
            elif isinstance(target, ast.Call):
                cn = call_name(mod, target)
                if cn in ("functools.partial", "partial") and target.args:
                    inner = target.args[0]
                    if isinstance(inner, ast.Name):
                        d = (local_defs.get(inner.id)
                             or _module_def(mod, inner.id))
                        if d is not None:
                            bound_idx = set(range(len(target.args) - 1))
                            bound_names = {
                                kw.arg for kw in target.keywords if kw.arg
                            }
                            yield (d, static_idx | bound_idx,
                                   static_names | bound_names, None)


def _decorator_jit(mod: ModuleInfo, dec: ast.AST):
    if _is_jit(mod, dec):
        return set(), set()
    if isinstance(dec, ast.Call):
        if _is_jit(mod, dec.func):
            return _static_kwargs(dec.keywords)
        cn = call_name(mod, dec)
        if cn in ("functools.partial", "partial") and dec.args:
            if _is_jit(mod, dec.args[0]):
                return _static_kwargs(dec.keywords)
    return None


def _scopes(mod: ModuleInfo):
    """(scope node, enclosing function-or-None) for module + every def."""
    yield mod.tree, None
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, node


def _module_def(mod: ModuleInfo, name: str):
    for n in mod.tree.body:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n.name == name:
            return n
    return None


# -- taint analysis within one jit entry ------------------------------------

def _params(node) -> List[str]:
    a = node.args
    names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
    return [n for n in names if n not in ("self", "cls")]


def _check_entry(project, mod, node, static_idx, static_names, result):
    a = node.args
    positional = [p.arg for p in (a.posonlyargs + a.args)]
    offset = 1 if positional[:1] in (["self"], ["cls"]) else 0
    taint: Set[str] = set()
    for i, p in enumerate(positional):
        if p in ("self", "cls"):
            continue
        if (i - offset) in static_idx or p in static_names:
            continue
        taint.add(p)
    for p in (x.arg for x in a.kwonlyargs):
        if p not in static_names:
            taint.add(p)
    if not taint:
        return

    # propagate through simple assignments to a fixpoint
    changed = True
    while changed:
        changed = False
        for n in ast.walk(node):
            target_names: List[str] = []
            value = None
            if isinstance(n, ast.Assign):
                value = n.value
                for t in n.targets:
                    target_names.extend(_name_targets(t))
            elif isinstance(n, ast.AugAssign) and isinstance(
                n.target, ast.Name
            ):
                value, target_names = n.value, [n.target.id]
            elif isinstance(n, ast.AnnAssign) and isinstance(
                n.target, ast.Name
            ) and n.value is not None:
                value, target_names = n.value, [n.target.id]
            if value is None or not target_names:
                continue
            if _tainted(value, taint):
                for t in target_names:
                    if t not in taint:
                        taint.add(t)
                        changed = True

    symbol = getattr(node, "name", "<lambda>")
    qual = f"{mod.name}.{symbol}"
    for n in ast.walk(node):
        test = None
        kind = None
        if isinstance(n, (ast.If, ast.While)):
            test, kind = n.test, "branches on"
        elif isinstance(n, ast.IfExp):
            test, kind = n.test, "branches on"
        elif isinstance(n, ast.Assert):
            test, kind = n.test, "asserts on"
        if test is not None and _tainted(test, taint):
            _emit(project, mod, n, qual, result,
                  f"{kind} the value of traced "
                  f"`{_first_tainted(test, taint)}` under jit — shape/"
                  "dtype are static, values are not")
            continue
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id in ("int", "float", "bool") and n.args:
            if any(_tainted(arg, taint) for arg in n.args):
                _emit(project, mod, n, qual, result,
                      f"`{n.func.id}()` concretizes traced "
                      f"`{_first_tainted(n.args[0], taint)}` under jit")
            continue
        if isinstance(n, ast.For) and _tainted(n.iter, taint):
            _emit(project, mod, n, qual, result,
                  "iterates over traced "
                  f"`{_first_tainted(n.iter, taint)}` under jit — the "
                  "loop unrolls per concrete length")


def _name_targets(t: ast.AST) -> List[str]:
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out = []
        for e in t.elts:
            out.extend(_name_targets(e))
        return out
    return []


def _tainted(expr: ast.AST, taint: Set[str]) -> bool:
    if isinstance(expr, ast.Attribute):
        if expr.attr in _STATIC_ATTRS:
            return False
        return _tainted(expr.value, taint)
    if isinstance(expr, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops
    ) and all(
        isinstance(c, ast.Constant) and c.value is None
        for c in expr.comparators
    ):
        # `x is None` tests pytree *structure*, which is static per trace
        return False
    if isinstance(expr, ast.Call):
        fname = dotted(expr.func)
        if fname in _STATIC_CALLS:
            return False
        return any(_tainted(c, taint) for c in ast.iter_child_nodes(expr))
    if isinstance(expr, ast.Name):
        return expr.id in taint
    if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return False
    return any(_tainted(c, taint) for c in ast.iter_child_nodes(expr))


def _first_tainted(expr: ast.AST, taint: Set[str]) -> str:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id in taint:
            return n.id
    return "<value>"


def _emit(project, mod, node, symbol, result, message):
    f = project.finding("RECOMPILE", mod, node, symbol, message,
                        suppressed_sink=result.suppressed)
    if f is not None:
        result.findings.append(f)


# -- mutable-capture check for inline-jitted closures -----------------------

def _check_closure(project, mod, fn_node, enclosing, result):
    local = set(_params(fn_node))
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            local.add(n.id)

    mutable_outer: Dict[str, ast.AST] = {}
    for n in walk_scope(enclosing):
        if isinstance(n, ast.Assign) and isinstance(n.value, (
                ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                ast.SetComp)):
            for t in n.targets:
                for name in _name_targets(t):
                    mutable_outer[name] = n
        elif isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            cn = dotted(n.value.func)
            if cn in _MUTABLE_CTORS:
                for t in n.targets:
                    for name in _name_targets(t):
                        mutable_outer[name] = n

    symbol = f"{mod.name}.{getattr(fn_node, 'name', '<lambda>')}"
    reported = set()
    for n in ast.walk(fn_node):
        if (
            isinstance(n, ast.Name)
            and isinstance(n.ctx, ast.Load)
            and n.id in mutable_outer
            and n.id not in local
            and n.id not in reported
        ):
            reported.add(n.id)
            f = project.finding(
                "RECOMPILE", mod, n, symbol,
                f"jit-compiled closure captures mutable Python container "
                f"`{n.id}` from the enclosing scope — not a stable jit "
                "cache key, and mutations after trace are invisible",
                suppressed_sink=result.suppressed,
            )
            if f is not None:
                result.findings.append(f)
