"""TRACED — the observability-coverage contract, statically.

Generalizes ``tests/test_trace_coverage.py`` (now a thin wrapper over
this checker) from runtime introspection to AST:

* every canonical entry point (build/search/fit/... — the
  :data:`ENTRY_NAMES` list) exported through the ``neighbors`` /
  ``cluster`` package ``__all__`` must carry the ``@traced`` decorator,
* the serve online surface (:data:`SERVE_ENTRY_POINTS`) must carry
  ``@traced("<exact label>")`` — a latency excursion with no span, or
  two surfaces sharing a label, makes the obs story unreadable,
* explicit ``@traced("...")`` labels must be unique project-wide,
* the pipelined dispatch path must keep its detached-span and
  request-id plumbing (``open_span``/``finish_span`` across threads,
  ``req_id`` through ``_Request.__slots__``, ``_record_flight`` with
  member ``request_ids`` on both dispatch paths),
* the ragged descriptor plumbing must stay intact: per-request ``k`` /
  ``fid`` ride ``_Request.__slots__`` into ``_invoke``'s descriptor
  columns (``row_k`` / ``row_fid``), flight records carry member
  ``fid``s, and the continuous-admission worker keeps its
  ``sem_held`` slot-before-batch handoff.

Discovery counts land in ``result.stats`` so the tier-1 test can
assert the contract is not vacuously green.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from raft_tpu.analysis.model import ModuleInfo, Project, dotted

#: canonical entry-point names inside exported backend modules — a
#: helper named anything else is free to stay untraced; anything on
#: this list is user-facing API surface and must report spans
ENTRY_NAMES = {
    "build", "build_batch", "search", "extend",
    "knn", "knn_query", "all_knn_query", "eps_nn",
    "fit", "fit_sharded", "predict", "fit_predict", "transform",
    "save", "load", "serialize_to_hnswlib",
}

#: packages (matched by dotted suffix) whose ``__all__`` defines the
#: traced API surface
API_PACKAGES = ("neighbors", "cluster")

#: online (method) entry points and the span label each must carry —
#: additions to the serve API surface belong on this list
SERVE_ENTRY_POINTS = {
    ("serve.service.SearchService", "search"): "serve.search",
    ("serve.service.SearchService", "explain"): "serve.explain",
    ("serve.service.SearchService", "swap"): "serve.swap",
    ("serve.service.SearchService", "warmup"): "serve.warmup",
    ("serve.service.SearchService", "flush"): "serve.flush",
    ("serve.mutation.MutableIndex", "upsert"): "serve.upsert",
    ("serve.mutation.MutableIndex", "delete"): "serve.delete",
    ("serve.ragged.RaggedSearcher", "__call__"): "serve.ragged.dispatch",
    ("serve.compactor.Compactor", "compact"): "serve.compact",
    ("serve.compactor.Compactor", "promote"): "serve.compact.promote",
    ("serve.compactor.Compactor", "abort"): "serve.compact.abort",
    ("serve.compactor.Compactor", "rebuild_sharded"):
        "serve.compact.rebuild_sharded",
    ("obs.slo.SloEngine", "evaluate_once"): "slo.evaluate",
    ("obs.incidents.IncidentManager", "handle_event"): "incidents.ingest",
    ("serve.overload.AdmissionController", "decide"):
        "serve.admission.decide",
    ("serve.overload.DegradedModeManager", "step"): "serve.degrade.step",
    ("serve.overload.HedgedDispatcher", "dispatch"): "serve.hedge.dispatch",
    ("obs.autotune.Autotuner", "step"): "autotune.step",
    ("serve.effort.EffortArbiter", "apply"): "serve.effort.apply",
    ("obs.perf.PerfLedger", "record"): "perf.record",
    ("obs.perf.PerfLedger", "evaluate"): "perf.evaluate",
    ("store.tiered.TieredStore", "ensure_resident"): "store.pager.ensure",
    ("store.tiered.TieredStore", "prefetch"): "store.pager.prefetch",
    ("store.tiered.TieredStore", "evict"): "store.pager.evict",
    ("obs.explain.QueryArchive", "record"): "explain.record",
    ("obs.explain.QueryArchive", "dump"): "explain.dump",
    ("obs.gateway.OperationalGateway", "dispatch"): "gateway.request",
}

#: module-level (function) serve entry points and their span labels —
#: the distributed build surface lives on functions, not classes
SERVE_FUNCTION_ENTRY_POINTS = {
    ("serve.build", "build_sharded"): "serve.build",
    ("serve.build", "knn_graph_sharded"): "serve.build.knn_graph",
}

#: the closed ``kernel_path`` vocabulary (tabulated in docs/kernels.md) —
#: the batcher, the perf ledger's hotspot keys, and the bench records all
#: treat the stamp as an enum; a stray literal would silently mint a new
#: ledger key that no dashboard or A/B gate knows to read
KERNEL_PATH_VOCAB = frozenset(
    {"pallas", "xla", "xla_filter_fallback", "sharded", "sharded_graph"}
)


def check(project: Project, result) -> None:
    entry_points = _api_entry_points(project)
    result.stats["traced_entry_points"] = len(entry_points)
    for qual, (mod, node) in sorted(entry_points.items()):
        if _traced_label(mod, node) is _UNTRACED:
            f = project.finding(
                "TRACED", mod, node, qual,
                "exported entry point lacks @traced — it would ship "
                "unobservable (no span, no latency series)",
                suppressed_sink=result.suppressed,
            )
            if f is not None:
                result.findings.append(f)

    _check_serve_labels(project, result)
    _check_label_uniqueness(project, result)
    _check_batcher_plumbing(project, result)
    _check_kernel_dispatch(project, result)


# -- API-surface discovery through package __all__ --------------------------

def _api_entry_points(
    project: Project,
) -> Dict[str, Tuple[ModuleInfo, ast.AST]]:
    out: Dict[str, Tuple[ModuleInfo, ast.AST]] = {}
    for suffix in API_PACKAGES:
        for pkg in project.modules_matching(suffix):
            exports = _all_literal(pkg)
            if exports is None:
                continue
            for name in exports:
                target = pkg.imports.get(name)
                if target is None:
                    continue
                if target in project.modules:
                    # module export: its ENTRY_NAMES defs are the surface
                    sub = project.modules[target]
                    for qual, node in _module_entry_defs(project, sub):
                        out[qual] = (sub, node)
                else:
                    # function export: from pkg.mod import fn
                    mod_name, _, fn_name = target.rpartition(".")
                    sub = project.modules.get(mod_name)
                    if sub is None:
                        continue
                    fn = project.functions.get(f"{mod_name}.{fn_name}")
                    if fn is not None and fn.class_name is None:
                        out[f"{mod_name}.{fn_name}"] = (sub, fn.node)
    return out


def _all_literal(mod: ModuleInfo) -> Optional[List[str]]:
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "__all__"
            for t in node.targets
        ):
            if isinstance(node.value, (ast.List, ast.Tuple)):
                return [
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                ]
    return None


def _module_entry_defs(project: Project, mod: ModuleInfo):
    """(qualname, def node) for entry-point functions a module exposes —
    its own top-level defs plus project-internal re-exports."""
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in ENTRY_NAMES:
            yield f"{mod.name}.{node.name}", node
    for alias, target in mod.imports.items():
        if alias not in ENTRY_NAMES:
            continue
        mod_name, _, fn_name = target.rpartition(".")
        fn = project.functions.get(target)
        if fn is not None and fn.class_name is None \
                and mod_name in project.modules:
            yield target, fn.node


# -- decorator inspection ---------------------------------------------------

_UNTRACED = object()


def _is_traced_ref(mod: ModuleInfo, node: ast.AST) -> bool:
    name = dotted(node)
    if name is None:
        return False
    head, _, rest = name.partition(".")
    resolved = mod.imports.get(head, head) + ("." + rest if rest else "")
    return resolved.endswith("core.trace.traced") or resolved == "traced"


def _traced_label(mod: ModuleInfo, node: ast.AST):
    """The explicit label, None for default-labelled, _UNTRACED if the
    def carries no @traced at all."""
    for dec in getattr(node, "decorator_list", []):
        if _is_traced_ref(mod, dec):
            return None
        if isinstance(dec, ast.Call) and _is_traced_ref(mod, dec.func):
            if dec.args and isinstance(dec.args[0], ast.Constant):
                return dec.args[0].value
            return None
    return _UNTRACED


def _check_serve_labels(project: Project, result) -> None:
    checked = 0
    for (cls_suffix, meth), label in sorted(SERVE_ENTRY_POINTS.items()):
        for cls in project.classes_matching(cls_suffix):
            checked += 1
            fn = project.functions.get(f"{cls.qualname}.{meth}")
            if fn is None:
                f = project.finding(
                    "TRACED", cls.module, cls.node, f"{cls.qualname}.{meth}",
                    f"serve entry point {meth} is missing from "
                    f"{cls.node.name} (the online span contract lists it)",
                    suppressed_sink=result.suppressed,
                )
            else:
                got = _traced_label(cls.module, fn.node)
                if got == label:
                    continue
                what = (
                    "lacks @traced" if got is _UNTRACED
                    else f"carries span label {got!r}"
                )
                f = project.finding(
                    "TRACED", cls.module,
                    fn.node if fn is not None else cls.node,
                    f"{cls.qualname}.{meth}",
                    f"serve entry point {what}, expected "
                    f"@traced({label!r})",
                    suppressed_sink=result.suppressed,
                )
            if f is not None:
                result.findings.append(f)
    for (mod_suffix, fn_name), label in sorted(
        SERVE_FUNCTION_ENTRY_POINTS.items()
    ):
        for mod in project.modules_matching(mod_suffix):
            checked += 1
            fn = project.functions.get(f"{mod.name}.{fn_name}")
            if fn is None or fn.class_name is not None:
                f = project.finding(
                    "TRACED", mod, mod.tree, f"{mod.name}.{fn_name}",
                    f"serve entry point {fn_name} is missing from "
                    f"{mod.name} (the online span contract lists it)",
                    suppressed_sink=result.suppressed,
                )
            else:
                got = _traced_label(mod, fn.node)
                if got == label:
                    continue
                what = (
                    "lacks @traced" if got is _UNTRACED
                    else f"carries span label {got!r}"
                )
                f = project.finding(
                    "TRACED", mod, fn.node, f"{mod.name}.{fn_name}",
                    f"serve entry point {what}, expected "
                    f"@traced({label!r})",
                    suppressed_sink=result.suppressed,
                )
            if f is not None:
                result.findings.append(f)
    result.stats["traced_serve_entries_checked"] = checked


def _check_label_uniqueness(project: Project, result) -> None:
    seen: Dict[str, str] = {}
    for fn in sorted(project.functions.values(), key=lambda f: f.qualname):
        label = _traced_label(fn.module, fn.node)
        if label is _UNTRACED or label is None:
            continue
        if label in seen:
            f = project.finding(
                "TRACED", fn.module, fn.node, fn.qualname,
                f"span label {label!r} reused (also on {seen[label]}) — "
                "two surfaces would merge into one latency series",
                suppressed_sink=result.suppressed,
            )
            if f is not None:
                result.findings.append(f)
        else:
            seen[label] = fn.qualname
    result.stats["traced_labels"] = len(seen)


# -- kernel dispatch attribution --------------------------------------------

def _stamp_literals(node: ast.AST) -> Optional[List[str]]:
    """String literals a ``stamp_kernel_path`` argument can evaluate to
    (handles the ``"a" if cond else "b"`` routing idiom); None when the
    value is not statically enumerable."""
    if isinstance(node, ast.Constant):
        return [node.value] if isinstance(node.value, str) else None
    if isinstance(node, ast.IfExp):
        body = _stamp_literals(node.body)
        orelse = _stamp_literals(node.orelse)
        if body is None or orelse is None:
            return None
        return body + orelse
    return None


def _check_kernel_dispatch(project: Project, result) -> None:
    """Per-dispatch attribution over the Pallas kernel entry points:

    * every ``stamp_kernel_path(...)`` call stamps a literal from the
      closed :data:`KERNEL_PATH_VOCAB` (a non-enumerable stamp would mint
      unreadable ledger keys at runtime);
    * every ``pallas_call`` under ``kernels.`` carries a
      ``cost_estimate=`` — without it the dispatch is an opaque custom
      call with blank flops/bytes/roofline columns in
      ``PerfLedger.top_hotspots()`` (ops/cost.py owns the formulas).
    """
    n_stamps = 0
    n_calls = 0
    for mod in sorted(project.modules.values(), key=lambda m: m.name):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            tail = name.rsplit(".", 1)[-1]
            if tail.lstrip("_") == "stamp_kernel_path" and node.args:
                n_stamps += 1
                vals = _stamp_literals(node.args[0])
                bad = (
                    "non-literal kernel_path" if vals is None
                    else ", ".join(
                        repr(v) for v in vals if v not in KERNEL_PATH_VOCAB
                    )
                )
                if bad:
                    f = project.finding(
                        "TRACED", mod, node, mod.name,
                        f"stamp_kernel_path({bad}) is outside the closed "
                        f"vocabulary {sorted(KERNEL_PATH_VOCAB)} — ledger "
                        "keys and bench A/B gates read the stamp as an "
                        "enum",
                        suppressed_sink=result.suppressed,
                    )
                    if f is not None:
                        result.findings.append(f)
            elif tail == "pallas_call" and ".kernels." in f".{mod.name}.":
                n_calls += 1
                if not any(kw.arg == "cost_estimate" for kw in node.keywords):
                    f = project.finding(
                        "TRACED", mod, node, mod.name,
                        "pallas_call without cost_estimate= — the dispatch "
                        "is an opaque custom call to XLA's cost model, so "
                        "its pallas ledger key reports blank flops/bytes/"
                        "roofline (register a formula in ops/cost.py)",
                        suppressed_sink=result.suppressed,
                    )
                    if f is not None:
                        result.findings.append(f)
    result.stats["traced_kernel_path_stamps"] = n_stamps
    result.stats["traced_pallas_cost_estimates"] = n_calls


# -- batcher detached-span / request-id plumbing ----------------------------

def _contains_identifier(node: ast.AST, ident: str) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id == ident:
            return True
        if isinstance(n, ast.Attribute) and n.attr == ident:
            return True
        if isinstance(n, ast.keyword) and n.arg == ident:
            return True
        if isinstance(n, ast.Constant) and n.value == ident:
            return True
    return False


def _check_batcher_plumbing(project: Project, result) -> None:
    classes = project.classes_matching("serve.batcher.MicroBatcher")
    result.stats["traced_batcher_classes"] = len(classes)
    for cls in classes:
        mod = cls.module

        def method(name: str):
            return project.functions.get(f"{cls.qualname}.{name}")

        def require(fn_name: str, ident: str, why: str):
            fn = method(fn_name)
            if fn is None:
                return  # absence of the method is its own refactor signal
            if not _contains_identifier(fn.node, ident):
                f = project.finding(
                    "TRACED", mod, fn.node, fn.qualname,
                    f"{fn_name} no longer references `{ident}` — {why}",
                    suppressed_sink=result.suppressed,
                )
                if f is not None:
                    result.findings.append(f)

        require("_dispatch_pipelined", "open_span",
                "the detached serve.batch span must open at dispatch")
        require("_dispatch_pipelined", "finish_span",
                "the dispatch failure path must close the span it opened")
        require("_complete", "finish_span",
                "the completion thread must close the detached span")
        require("submit", "next_request_id",
                "every request gets a process-wide id at submit")
        require("submit", "request_id",
                "the id must be exposed on the returned future")
        for path in ("_dispatch_locked", "_complete"):
            require(path, "_record_flight",
                    "both dispatch paths must feed the flight recorder")
            require(path, "request_ids",
                    "batch records must carry member request ids")
        require("_record_flight", "req_id",
                "member request ids must cross into batch records")

        # ragged descriptor plumbing: per-request k/fid must ride the
        # dispatch as data columns and land in flight records
        require("_invoke_args", "row_k",
                "ragged dispatches must pass the per-request k column")
        require("_invoke_args", "row_fid",
                "ragged dispatches must pass the per-request filter-id "
                "column")
        require("_record_flight", "fid",
                "ragged batch records must carry member filter ids")
        require("_worker", "sem_held",
                "continuous admission claims the in-flight slot before "
                "cutting the batch")

        # overload plumbing: every batch cut must pass through the
        # admission gate (shed/expire decisions are made at cut time,
        # not at submit), and priority/deadline must enter at submit
        require("submit", "priority",
                "requests must carry their priority class from submit")
        require("submit", "deadline",
                "requests must carry their absolute deadline from submit")
        for path in ("_worker", "flush"):
            require(path, "_admit",
                    "every batch cut must pass the admission gate "
                    "(deadline expiry + priority shedding)")

        # _Request.__slots__ must carry req_id so ids cross the queue,
        # and the ragged descriptor fields k / fid alongside it
        for req_cls in project.classes_matching(
            f"{mod.name.rsplit('.', 1)[-1]}._Request"
        ):
            if req_cls.module is not mod:
                continue
            slots = _class_slots(req_cls.node)
            if slots is None:
                continue
            for slot, why in (
                ("req_id", "request ids cannot cross the queue"),
                ("k", "per-request k cannot cross the queue"),
                ("fid", "per-request filter ids cannot cross the queue"),
                ("priority", "priority classes cannot cross the queue — "
                 "admission would shed blind"),
                ("deadline", "deadlines cannot cross the queue — expired "
                 "work would dispatch anyway"),
            ):
                if slot in slots:
                    continue
                f = project.finding(
                    "TRACED", mod, req_cls.node, req_cls.qualname,
                    f"_Request dropped its {slot} slot; {why}",
                    suppressed_sink=result.suppressed,
                )
                if f is not None:
                    result.findings.append(f)


def _class_slots(node: ast.ClassDef) -> Optional[Set[str]]:
    for item in node.body:
        if isinstance(item, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "__slots__"
            for t in item.targets
        ):
            if isinstance(item.value, (ast.Tuple, ast.List)):
                return {
                    e.value for e in item.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                }
    return None
