"""LOCKORDER — static lock-acquisition graph + guarded-attribute writes.

Two sub-checks over the lock inventory the project model collected:

**Acquisition-order cycles.**  An edge A→B exists when code acquires B
(lexically nested ``with``, or a call made while holding A whose callee
may acquire B — transitively over resolved call edges).  Any strongly
connected component in that graph is an ordering hazard: two threads
taking the component's locks from different entry points can deadlock.
A self-edge on a non-reentrant ``threading.Lock`` (re-acquiring while
holding, directly or through a call chain) is reported the same way.

**Guarded-attribute discipline.**  Within a class, any attribute
written inside a ``with self.<lock>`` block anywhere is lock-guarded;
every other write to it must hold one of its guarding locks.
``__init__`` (no concurrent readers yet) and ``*_locked`` methods
(named convention: caller holds the lock) are exempt.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from raft_tpu.analysis.model import (
    ClassInfo,
    FunctionInfo,
    Project,
)

_NONREENTRANT = {"Lock"}


def _class_of(project: Project, fn: FunctionInfo) -> Optional[ClassInfo]:
    if fn.class_name is None:
        return None
    return project.classes.get(f"{fn.module.name}.{fn.class_name}")


def _lock_id(project: Project, fn: FunctionInfo, expr: ast.AST) -> Optional[str]:
    """Canonical lock identity of a ``with`` subject, when recognizable."""
    cls = _class_of(project, fn)
    if (
        cls is not None
        and isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        attr = cls.lock_aliases.get(expr.attr, expr.attr)
        if attr in cls.lock_attrs:
            return f"{cls.qualname}.{attr}"
    if isinstance(expr, ast.Name) and expr.id in fn.module.module_locks:
        return f"{fn.module.name}.{expr.id}"
    return None


def _lock_ctor(project: Project, lock_id: str) -> str:
    owner, _, attr = lock_id.rpartition(".")
    cls = project.classes.get(owner)
    if cls is not None:
        return cls.lock_attrs.get(attr, "?")
    mod = project.modules.get(owner)
    if mod is not None:
        return mod.module_locks.get(attr, "?")
    return "?"


def check(project: Project, result) -> None:
    # pass 1: per-function direct acquisitions, lexical nesting edges and
    # calls made while holding a lock
    direct: Dict[str, Set[str]] = {q: set() for q in project.functions}
    edges: Dict[Tuple[str, str], Tuple[FunctionInfo, ast.AST]] = {}
    calls_holding: List[Tuple[FunctionInfo, str, str, ast.AST]] = []

    for fn in project.functions.values():
        _scan_fn(project, fn, direct, edges, calls_holding)

    # pass 2: transitive may-acquire over resolved call edges
    may: Dict[str, Set[str]] = {q: set(s) for q, s in direct.items()}
    changed = True
    while changed:
        changed = False
        for fn in project.functions.values():
            acc = may[fn.qualname]
            before = len(acc)
            for callee in fn.calls:
                acc |= may.get(callee, set())
            changed = changed or len(acc) != before

    for fn, held, callee, node in calls_holding:
        for target in sorted(may.get(callee, ())):
            edges.setdefault((held, target), (fn, node))

    result.stats["lockorder_locks"] = len(
        {l for pair in edges for l in pair}
        | {l for s in direct.values() for l in s}
    )
    result.stats["lockorder_edges"] = len(edges)

    _report_cycles(project, edges, result)
    for cls in sorted(project.classes.values(), key=lambda c: c.qualname):
        if cls.lock_attrs:
            _check_guarded_attrs(project, cls, result)


def _scan_fn(project, fn, direct, edges, calls_holding) -> None:
    def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            # nested defs run at call time, not under this lexical lock
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                lid = _lock_id(project, fn, item.context_expr)
                if lid is None:
                    continue
                direct[fn.qualname].add(lid)
                for h in new_held:
                    edges.setdefault((h, lid), (fn, node))
                new_held = new_held + (lid,)
            for child in node.body:
                visit(child, new_held)
            return
        if isinstance(node, ast.Call) and held:
            callee = project._resolve_callee(fn, fn.module, node.func)
            if callee is not None:
                for h in held:
                    calls_holding.append((fn, h, callee, node))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.node.body:
        visit(stmt, ())


def _report_cycles(project: Project, edges, result) -> None:
    adj: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())

    for scc in _sccs(adj):
        cyclic = len(scc) > 1 or (len(scc) == 1 and scc[0] in adj[scc[0]])
        if not cyclic:
            continue
        if len(scc) == 1:
            lock = scc[0]
            if _lock_ctor(project, lock) not in _NONREENTRANT:
                continue  # RLock/Condition re-acquisition is legal
            fn, node = edges[(lock, lock)]
            f = project.finding(
                "LOCKORDER", fn.module, node, fn.qualname,
                f"re-acquires non-reentrant lock {lock} while holding it "
                "(direct or through the call chain) — self-deadlock",
                suppressed_sink=result.suppressed,
            )
        else:
            cycle = sorted(scc)
            site = None
            for a in cycle:
                for b in cycle:
                    if (a, b) in edges:
                        site = edges[(a, b)]
                        break
                if site:
                    break
            fn, node = site
            f = project.finding(
                "LOCKORDER", fn.module, node, fn.qualname,
                "lock-acquisition cycle (threads entering from different "
                f"points can deadlock): {' ⇄ '.join(cycle)}",
                suppressed_sink=result.suppressed,
            )
        if f is not None:
            result.findings.append(f)


def _sccs(adj: Dict[str, Set[str]]) -> List[List[str]]:
    """Tarjan, iterative (the graph is tiny but recursion limits are
    cheap to avoid)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(sorted(adj[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj[w]))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(comp)
    return out


def _check_guarded_attrs(project: Project, cls: ClassInfo, result) -> None:
    # (attr, held-locks, method, node) for every self.<attr> write
    writes: List[Tuple[str, Tuple[str, ...], FunctionInfo, ast.AST]] = []

    methods = [
        fn for fn in project.functions.values()
        if fn.module is cls.module and fn.class_name == cls.node.name
    ]

    for fn in methods:
        def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in node.items:
                    lid = _lock_id(project, fn, item.context_expr)
                    if lid is not None:
                        new_held = new_held + (lid,)
                for child in node.body:
                    visit(child, new_held)
                return
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for tgt in targets:
                for leaf in ast.walk(tgt):
                    if (
                        isinstance(leaf, ast.Attribute)
                        and isinstance(leaf.ctx, ast.Store)
                        and isinstance(leaf.value, ast.Name)
                        and leaf.value.id == "self"
                    ):
                        writes.append((leaf.attr, held, fn, node))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.node.body:
            visit(stmt, ())

    guards: Dict[str, Set[str]] = {}
    for attr, held, fn, node in writes:
        if held:
            guards.setdefault(attr, set()).update(held)

    for attr, held, fn, node in writes:
        if attr not in guards or held:
            continue
        if fn.name == "__init__" or fn.name.endswith("_locked"):
            continue
        lock_names = ", ".join(sorted(guards[attr]))
        f = project.finding(
            "LOCKORDER", fn.module, node, f"{fn.qualname}",
            f"writes lock-guarded attribute self.{attr} without holding "
            f"its lock (guarded elsewhere by {lock_names})",
            suppressed_sink=result.suppressed,
        )
        if f is not None:
            result.findings.append(f)
