"""ENVREG — every ``RAFT_TPU_*`` knob goes through the typed registry.

Three reconciliations, all static:

1. **No stray reads.**  Outside ``core/env.py`` itself, any literal
   ``RAFT_TPU_*`` read through ``os.environ.get`` / ``os.getenv`` /
   ``os.environ[...]`` / ``"X" in os.environ`` must migrate to the
   :mod:`raft_tpu.core.env` accessors (``env.has``/``env.raw`` cover
   membership and save-restore).  The handful of bootstrap reads that
   must run before the package can import carry inline suppressions.
2. **Accessor names are declared.**  Accessor call sites with a
   literal name must reference a ``KNOWN_VARS`` row (parsed from the
   AST of ``core/env.py``, never imported) and with the declared type
   (``env_int`` against a ``float`` row is drift).
3. **README table ↔ registry.**  Every declared var appears in the
   README env table and vice versa — docs cannot go stale silently.
   Skipped when the scan root has no ``core/env.py``/README (fixtures).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Optional, Tuple

from raft_tpu.analysis.model import ModuleInfo, Project, call_name, dotted

_VAR_RE = re.compile(r"RAFT_TPU_[A-Z0-9_]+")

_ACCESSORS = {
    "env_str": "str",
    "env_int": "int",
    "env_float": "float",
    "env_bool": "bool",
    "has": None,      # type-agnostic
    "raw": None,
}


def check(project: Project, result) -> None:
    registry = _load_registry(project)
    result.stats["envreg_known_vars"] = len(registry or {})

    for mod in sorted(project.modules.values(), key=lambda m: m.name):
        if mod.name.endswith("core.env"):
            continue
        _check_direct_reads(project, mod, result)
        if registry is not None:
            _check_accessor_calls(project, mod, registry, result)

    if registry is not None and project.readme:
        _check_readme(project, registry, result)


def _load_registry(project: Project) -> Optional[Dict[str, Tuple[str, int]]]:
    """name → (kind, lineno) parsed from core/env.py's KNOWN_VARS."""
    mods = project.modules_matching("core.env")
    if not mods:
        return None
    mod = mods[0]
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "KNOWN_VARS"
            for t in targets
        ):
            continue
        out: Dict[str, Tuple[str, int]] = {}
        if isinstance(node.value, (ast.Tuple, ast.List)):
            for item in node.value.elts:
                if not (isinstance(item, ast.Call) and item.args):
                    continue
                name = item.args[0]
                kind = item.args[1] if len(item.args) > 1 else None
                if isinstance(name, ast.Constant) and isinstance(
                    name.value, str
                ):
                    k = (
                        kind.value
                        if isinstance(kind, ast.Constant) else "str"
                    )
                    out[name.value] = (k, item.lineno)
        return out
    return None


def _literal_env_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith("RAFT_TPU_"):
        return node.value
    return None


def _check_direct_reads(project: Project, mod: ModuleInfo, result) -> None:
    for node in ast.walk(mod.tree):
        var = None
        how = None
        if isinstance(node, ast.Call):
            cn = call_name(mod, node)
            if cn == "os.getenv" and node.args:
                var, how = _literal_env_name(node.args[0]), "os.getenv"
            elif cn in ("os.environ.get", "environ.get") and node.args:
                var, how = _literal_env_name(node.args[0]), "os.environ.get"
        elif isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Load
        ):
            if dotted(node.value) in ("os.environ", "environ"):
                var, how = _literal_env_name(node.slice), "os.environ[...]"
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)):
            if dotted(node.comparators[0]) in ("os.environ", "environ"):
                var, how = (
                    _literal_env_name(node.left), "membership in os.environ"
                )
        if var is None:
            continue
        f = project.finding(
            "ENVREG", mod, node, var,
            f"direct {how} read of {var}; route it through the typed "
            "raft_tpu.core.env accessors so the registry and README "
            "stay reconciled",
            suppressed_sink=result.suppressed,
        )
        if f is not None:
            result.findings.append(f)


def _check_accessor_calls(project, mod, registry, result) -> None:
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        cn = call_name(mod, node)
        if cn is None:
            continue
        accessor = cn.rsplit(".", 1)[-1]
        if accessor not in _ACCESSORS:
            continue
        if not (
            cn == f"raft_tpu.core.env.{accessor}"
            or cn.endswith(f"core.env.{accessor}")
            or cn == f"env.{accessor}"
        ):
            continue
        var = _literal_env_name(node.args[0])
        if var is None:
            continue
        if var not in registry:
            f = project.finding(
                "ENVREG", mod, node, var,
                f"{accessor}({var!r}) reads a variable not declared in "
                "core/env.py KNOWN_VARS; add a registry row (and README "
                "entry)",
                suppressed_sink=result.suppressed,
            )
            if f is not None:
                result.findings.append(f)
            continue
        expected = _ACCESSORS[accessor]
        declared = registry[var][0]
        if expected is not None and expected != declared:
            f = project.finding(
                "ENVREG", mod, node, var,
                f"{accessor}({var!r}) disagrees with the registry, which "
                f"declares {var} as {declared!r}",
                suppressed_sink=result.suppressed,
            )
            if f is not None:
                result.findings.append(f)


def _check_readme(project: Project, registry, result) -> None:
    with open(project.readme, encoding="utf-8") as f:
        lines = f.readlines()
    documented: Dict[str, int] = {}
    for lineno, line in enumerate(lines, start=1):
        if not line.lstrip().startswith("|"):
            continue
        first_cell = line.split("|")[1] if line.count("|") >= 2 else ""
        for var in _VAR_RE.findall(first_cell):
            documented.setdefault(var, lineno)

    env_mod = project.modules_matching("core.env")[0]
    anchor = ast.Module(body=[], type_ignores=[])  # line 0 fallback

    for var, (kind, lineno) in sorted(registry.items()):
        if var not in documented:
            site = ast.copy_location(ast.Pass(), env_mod.tree.body[0])
            site.lineno = lineno
            site.end_lineno = lineno
            f = project.finding(
                "ENVREG", env_mod, site, var,
                f"{var} is declared in KNOWN_VARS but missing from the "
                "README environment-variable table",
                suppressed_sink=result.suppressed,
            )
            if f is not None:
                result.findings.append(f)

    for var, lineno in sorted(documented.items()):
        if var not in registry:
            site = ast.Pass()
            site.lineno = lineno
            site.end_lineno = lineno
            site.col_offset = 0
            readme_mod = ModuleInfo(
                name="README", path=_rel_readme(project), tree=anchor,
                source="", suppressions={},
            )
            f = project.finding(
                "ENVREG", readme_mod, site, var,
                f"README documents {var} but core/env.py KNOWN_VARS has "
                "no such row — stale docs or an undeclared knob",
                suppressed_sink=result.suppressed,
            )
            if f is not None:
                result.findings.append(f)


def _rel_readme(project: Project) -> str:
    import os

    return os.path.relpath(project.readme, project.base)
