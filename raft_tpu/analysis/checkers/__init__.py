"""Checker registry: rule name → ``check(project, result)``.

Each checker appends :class:`~raft_tpu.analysis.findings.Finding`
objects to ``result.findings`` (suppressed ones to
``result.suppressed``) and may record discovery counters in
``result.stats`` — the vacuity guards in the tier-1 test read those, so
a refactor that silently breaks discovery fails loudly instead of
green-lighting everything.
"""

from __future__ import annotations

from raft_tpu.analysis.checkers import (
    envreg,
    hostsync,
    lockorder,
    recompile,
    traced,
)

CHECKERS = {
    "RECOMPILE": recompile.check,
    "HOSTSYNC": hostsync.check,
    "LOCKORDER": lockorder.check,
    "ENVREG": envreg.check,
    "TRACED": traced.check,
}

__all__ = ["CHECKERS"]
