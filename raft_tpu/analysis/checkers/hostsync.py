"""HOSTSYNC — device→host synchronization on the serving hot path.

The serving pipeline's throughput story rests on dispatch staying
asynchronous: the only intended host syncs are the staged copy-out at
the end of a batch.  Anything else — ``.item()``, ``.tolist()``,
``.block_until_ready()``, ``jax.device_get``, ``np.asarray`` on a
device array, or ``float(x[0])`` — stalls the dispatch thread for a
full device round-trip and serializes the pipeline.

The checker computes the set of functions statically reachable from
the hot-path roots (MicroBatcher dispatch/completion, the shard-merge
and replica search paths) over resolved call edges and flags every
sync-shaped operation inside them.  Intended syncs carry an inline
``# raft-tpu: ignore[HOSTSYNC]`` with a reason.
"""

from __future__ import annotations

import ast

from raft_tpu.analysis.model import Project, call_name, dotted

#: hot-path roots, matched by dotted-qualname suffix so the fixture
#: package triggers the same contract
ROOTS = (
    "serve.batcher.MicroBatcher._dispatch_locked",
    "serve.batcher.MicroBatcher._dispatch_pipelined",
    "serve.batcher.MicroBatcher._complete",
    "serve.service.SearchService.search",
    "serve.mutation.MutableIndex.search",
    "serve.shard.ShardedIndex.search",
    "serve.replica.ReplicaGroup.search",
)

#: method calls that force a sync regardless of receiver type
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}

#: import-resolved call targets that force a sync / host copy
_SYNC_CALLS = {
    "jax.block_until_ready": "blocks until device work completes",
    "jax.device_get": "copies device buffers to host",
    "numpy.asarray": "materializes a device array on host",
    "numpy.array": "materializes a device array on host",
    "numpy.copy": "materializes a device array on host",
}


def check(project: Project, result) -> None:
    roots = []
    for suffix in ROOTS:
        roots.extend(project.functions_matching(suffix))
    result.stats["hostsync_roots"] = len(roots)
    reachable = project.reachable(roots)
    result.stats["hostsync_reachable"] = len(reachable)

    seen = set()
    for fn in sorted(reachable, key=lambda f: f.qualname):
        mod = fn.module
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            key = (mod.path, getattr(node, "lineno", 0),
                   getattr(node, "col_offset", 0))
            if key in seen:
                continue
            msg = _classify(mod, node)
            if msg is None:
                continue
            seen.add(key)
            f = project.finding(
                "HOSTSYNC", mod, node, fn.qualname,
                f"{msg} inside hot-path function",
                suppressed_sink=result.suppressed,
            )
            if f is not None:
                result.findings.append(f)


def _classify(mod, call: ast.Call):
    if isinstance(call.func, ast.Attribute):
        name = call_name(mod, call)
        if name in _SYNC_CALLS:
            return f"`{dotted(call.func)}` {_SYNC_CALLS[name]}"
        if call.func.attr in _SYNC_METHODS and dotted(call.func) is None:
            # method on a computed receiver (e.g. result.dist.item())
            return f"`.{call.func.attr}()` forces a device→host sync"
        if (
            call.func.attr in _SYNC_METHODS
            and name not in _SYNC_CALLS
            and not (name or "").startswith(("os.", "time.", "threading."))
        ):
            return f"`.{call.func.attr}()` forces a device→host sync"
    elif isinstance(call.func, ast.Name):
        if call.func.id in ("float", "int", "bool") and call.args:
            arg = call.args[0]
            if isinstance(arg, ast.Subscript) and not _static_chain(arg):
                return (
                    f"`{call.func.id}()` on an indexed array concretizes "
                    "a device value"
                )
    return None


#: attributes that are host-side metadata — int(x.shape[1]) never syncs
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes"}


def _static_chain(node: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS
        for n in ast.walk(node)
    )
