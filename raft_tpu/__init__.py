"""raft_tpu — a TPU-native library of ML / data-mining primitives and
vector-search (ANN) algorithms, built on JAX / XLA / Pallas.

This is a ground-up TPU-first re-design with the capabilities of RAFT
(Reusable Accelerated Functions and Tools, reference: /root/reference
README.md:1-45): pairwise distances, k-selection, k-means, brute-force and
approximate nearest-neighbor indexes (IVF-Flat, IVF-PQ, CAGRA), sparse
primitives, graph/spectral algorithms, stats, RNG, and a distributed
communication facade over XLA collectives.

Architecture (bottom → top), mirroring the reference's layer map
(SURVEY.md §1) but re-expressed for TPU:

- ``raft_tpu.core``      — resources/context, serialization, logging, bitset
                           (ref: cpp/include/raft/core/)
- ``raft_tpu.ops``       — dense linalg + matrix primitives incl. select_k
                           (ref: cpp/include/raft/{linalg,matrix}/)
- ``raft_tpu.distance``  — pairwise distances, fused L2 1-NN, Gram kernels
                           (ref: cpp/include/raft/distance/)
- ``raft_tpu.random``    — RNG + dataset generators (ref: cpp/include/raft/random/)
- ``raft_tpu.cluster``   — kmeans, balanced kmeans, single-linkage, spectral
                           (ref: cpp/include/raft/cluster/)
- ``raft_tpu.neighbors`` — brute_force / ivf_flat / ivf_pq / cagra / nn_descent
                           / refine (ref: cpp/include/raft/neighbors/)
- ``raft_tpu.sparse``    — COO/CSR types and sparse primitives
                           (ref: cpp/include/raft/sparse/)
- ``raft_tpu.stats``     — summary stats + model metrics incl. neighborhood_recall
                           (ref: cpp/include/raft/stats/)
- ``raft_tpu.comms``     — comms facade over XLA collectives (psum/all_gather/...)
                           (ref: cpp/include/raft/comms/, core/comms.hpp)
- ``raft_tpu.bench``     — ANN benchmark harness (ref: cpp/bench/ann/, raft-ann-bench)

Everything is functional and jit-friendly: static shapes, `lax` control flow,
sharding via `jax.sharding.Mesh` + shard_map.
"""

__version__ = "0.1.0"

from raft_tpu.core.resources import Resources, DeviceResources, default_resources

__all__ = [
    "Resources",
    "DeviceResources",
    "default_resources",
    "__version__",
]
