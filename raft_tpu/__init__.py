"""raft_tpu — a TPU-native library of ML / data-mining primitives and
vector-search (ANN) algorithms, built on JAX / XLA / Pallas.

This is a ground-up TPU-first re-design with the capabilities of RAFT
(Reusable Accelerated Functions and Tools, reference: /root/reference
README.md:1-45): pairwise distances, k-selection, k-means, brute-force and
approximate nearest-neighbor indexes (IVF-Flat, IVF-PQ, CAGRA), sparse
primitives, graph/spectral algorithms, stats, RNG, and a distributed
communication facade over XLA collectives.

Architecture (bottom → top), mirroring the reference's layer map
(SURVEY.md §1) but re-expressed for TPU:

- ``raft_tpu.core``      — resources/context, serialization, logging, bitset
                           (ref: cpp/include/raft/core/)
- ``raft_tpu.ops``       — dense linalg + matrix primitives incl. select_k
                           (ref: cpp/include/raft/{linalg,matrix}/)
- ``raft_tpu.distance``  — pairwise distances, fused L2 1-NN, Gram kernels
                           (ref: cpp/include/raft/distance/)
- ``raft_tpu.random``    — RNG + dataset generators (ref: cpp/include/raft/random/)
- ``raft_tpu.cluster``   — kmeans, balanced kmeans, single-linkage, spectral
                           (ref: cpp/include/raft/cluster/)
- ``raft_tpu.neighbors`` — brute_force / ivf_flat / ivf_pq / cagra / nn_descent
                           / refine (ref: cpp/include/raft/neighbors/)
- ``raft_tpu.sparse``    — COO/CSR types and sparse primitives
                           (ref: cpp/include/raft/sparse/)
- ``raft_tpu.stats``     — summary stats + model metrics incl. neighborhood_recall
                           (ref: cpp/include/raft/stats/)
- ``raft_tpu.comms``     — comms facade over XLA collectives (psum/all_gather/...)
                           (ref: cpp/include/raft/comms/, core/comms.hpp)
- ``raft_tpu.obs``       — observability: metrics registry, spans, XLA event
                           attribution, Prometheus/JSON export
                           (ref: core/nvtx.hpp + core/logger-inl.hpp, made queryable)
- ``raft_tpu.bench``     — ANN benchmark harness (ref: cpp/bench/ann/, raft-ann-bench)

Everything is functional and jit-friendly: static shapes, `lax` control flow,
sharding via `jax.sharding.Mesh` + shard_map.
"""

__version__ = "0.1.0"

import os as _os


def _enable_persistent_compile_cache() -> None:
    """Point JAX's persistent compilation cache at a package-local directory.

    Cold-process XLA compiles dominate wall time for index builds (measured:
    148 s cold vs 4 s warm for a 100k-row IVF-PQ build through the TPU
    tunnel), so caching compiled executables across processes is the single
    biggest end-to-end speedup available. Opt out with
    ``RAFT_TPU_NO_COMPILE_CACHE=1``; override the location with
    ``RAFT_TPU_CACHE_DIR``. No-ops gracefully on JAX versions without the
    config knobs.
    """
    if _os.environ.get("RAFT_TPU_NO_COMPILE_CACHE"):  # raft-tpu: ignore[ENVREG] package-init bootstrap, runs before core.env exists
        return
    if _os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return  # the user already routed the cache; don't override
    import jax

    try:
        if jax.config.jax_compilation_cache_dir is not None:
            return  # ditto for an in-process jax.config setting
    except AttributeError:
        pass
    # default to a user cache dir (XDG), never inside the installed package:
    # a pip install lands alongside site-packages, which may be read-only and
    # shouldn't accumulate state
    xdg = _os.environ.get("XDG_CACHE_HOME") or _os.path.join(
        _os.path.expanduser("~"), ".cache"
    )
    cache_dir = _os.environ.get("RAFT_TPU_CACHE_DIR") or _os.path.join(  # raft-tpu: ignore[ENVREG] package-init bootstrap
        xdg, "raft_tpu", "jax_cache"
    )
    try:
        _os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _os.path.abspath(cache_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # pragma: no cover - old JAX or read-only filesystem
        pass


_enable_persistent_compile_cache()

from raft_tpu.core.resources import Resources, DeviceResources, default_resources

__all__ = [
    "Resources",
    "DeviceResources",
    "default_resources",
    "__version__",
]
