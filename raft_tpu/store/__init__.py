"""raft_tpu.store — paged index storage with host/HBM tiering.

Monolithic device arrays cap index size at HBM and force whole-buffer
rebuilds on mutation.  This package stores the big payloads (IVF lists,
PQ decode caches, dataset rows) as fixed-size *pages* behind an int32
page table instead:

- :mod:`~raft_tpu.store.pagestore` — host cold tier: the authoritative
  padded page buffer, aliased back onto the index as its monolithic
  host view (serialization and compaction decode paths are unchanged).
- :mod:`~raft_tpu.store.tiered` — the HBM hot pool: a static device
  array + device page table with clock eviction, demand admission
  (``ensure_resident``) and bounded async prefetch keyed by the
  coarse-probe result.  Page movement rewrites values, never shapes —
  zero recompiles after warmup.
- :mod:`~raft_tpu.store.budget` — hard memory admission: reservations
  either fit ``RAFT_TPU_PAGE_HBM_BUDGET_MB`` or raise a loud
  :class:`BudgetExceeded`; the compactor's projected-bytes gate and
  serving share this one ledger.
- :mod:`~raft_tpu.store.paged` — jit-traversable paged views
  (:class:`PagedLists` / :class:`PagedRows`) that substitute for the
  monolithic payload inside the existing search executables, plus
  :func:`paginate_index` to convert a built index in place.

Enable per-service with ``RAFT_TPU_PAGED=1`` (the unpaged path is the
default-off control arm); see ``docs/paged_storage.md``.
"""

from raft_tpu.store.budget import (
    BudgetExceeded,
    MemoryBudget,
    default_budget,
    set_default_budget,
)
from raft_tpu.store.paged import (
    PagedLists,
    PagedRows,
    gather_lists,
    pages_for_lists,
    paginate_index,
)
from raft_tpu.store.pagestore import PageStore
from raft_tpu.store.tiered import TieredStore

__all__ = [
    "BudgetExceeded",
    "MemoryBudget",
    "PageStore",
    "PagedLists",
    "PagedRows",
    "TieredStore",
    "default_budget",
    "gather_lists",
    "pages_for_lists",
    "paginate_index",
    "set_default_budget",
]
