"""Fixed-size page layout over a host row array.

The "Ragged Paged Attention" recipe (PAPERS.md): ragged per-entity state
(here: IVF lists, PQ decode caches, dataset rows) is stored as fixed-
size pages addressed through an int32 page table, so residency and
movement operate on uniform blocks instead of per-list ragged buffers.

A :class:`PageStore` is the *cold tier*: host-RAM pages that remain the
authoritative copy of every row.  It owns one contiguous padded buffer;
``pages`` and the flat ``data`` array are reshaped views of the same
memory, so an index can keep its familiar monolithic host view (e.g.
``list_data [L, cap, d]``) aliased onto the paged layout with zero copy
and zero double-counting.
"""

from __future__ import annotations

import numpy as np

__all__ = ["PageStore"]


class PageStore:
    """Host pages over ``rows [n, ...]`` with ``page_rows`` rows/page.

    Attributes
    ----------
    data : np.ndarray
        ``[n_pages * page_rows, ...]`` — the padded flat buffer (rows
        past ``n_rows`` are zeros).  Views of this buffer are what the
        owning index aliases as its monolithic host arrays.
    pages : np.ndarray
        ``[n_pages, page_rows, ...]`` — reshaped view of ``data``.
    page_table : np.ndarray
        ``[n_pages] int32`` logical→storage page map.  Identity today;
        serialized so a future compacting writer can relocate pages
        without touching logical addresses.
    """

    def __init__(self, rows: np.ndarray, page_rows: int):
        rows = np.asarray(rows)
        if rows.ndim < 1:
            raise ValueError("rows must have at least one dimension")
        if page_rows < 1:
            raise ValueError(f"page_rows must be >= 1, got {page_rows}")
        n = rows.shape[0]
        self.n_rows = int(n)
        self.page_rows = int(page_rows)
        n_pages = max(1, -(-n // page_rows))
        payload = rows.shape[1:]
        self.data = np.zeros((n_pages * page_rows,) + payload, rows.dtype)
        self.data[:n] = rows
        self.pages = self.data.reshape((n_pages, page_rows) + payload)
        self.page_table = np.arange(n_pages, dtype=np.int32)

    @property
    def n_pages(self) -> int:
        return self.pages.shape[0]

    @property
    def page_bytes(self) -> int:
        return int(self.pages[0].nbytes)

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def nbytes(self) -> int:
        return int(self.data.nbytes) + int(self.page_table.nbytes)

    def page(self, i: int) -> np.ndarray:
        """One logical page's rows (a view, page-table indirected)."""
        return self.pages[self.page_table[i]]

    def gather(self, page_ids: np.ndarray) -> np.ndarray:
        """Rows of several logical pages, ``[len(page_ids), page_rows, ...]``."""
        return self.pages[self.page_table[np.asarray(page_ids, np.int64)]]

    def to_array(self) -> np.ndarray:
        """The original (unpadded) rows — a view when the page table is
        identity, a gathered copy after relocation."""
        if np.array_equal(self.page_table, np.arange(self.n_pages)):
            return self.data[: self.n_rows]
        flat = self.pages[self.page_table].reshape(self.data.shape)
        return flat[: self.n_rows]
