"""Paged device views + per-backend pagination.

Two jit-traversable pytrees substitute for the monolithic device payload
inside the *existing* search executables:

- :class:`PagedLists` stands in for a padded-list tensor
  ``[L, cap, payload]`` (ivf_flat ``list_data``, ivf_pq's decoded scan
  cache).  ``gather_lists(ld, pp)`` replaces the ``ld[pp]`` gather: for
  a paged view it routes each probe through the device page table
  (``pool[page_slot[list*ppl + j]]``), producing rows bit-identical to
  the monolithic gather for resident pages — everything downstream of
  the gather is unchanged, which is what makes paged search
  result-identical to the control arm.
- :class:`PagedRows` stands in for a flat row matrix ``[n, d]`` (cagra
  dataset); ``decode(ids)`` is the page-table translation of a row
  gather and slots straight into cagra's existing ``_gather_rows``
  decode branch.

:func:`paginate_index` converts a built backend index *in place*: the
big payload moves to a host :class:`~raft_tpu.store.pagestore.PageStore`
(cold tier, aliased back onto the index as its monolithic host array so
serialization / compaction decode paths are unchanged) fronted by a
budget-sized :class:`~raft_tpu.store.tiered.TieredStore` hot pool at
``index.paged``.  List capacity is repadded to a page multiple with the
build's own padding values (ids −1, norms +inf, rows 0), so the extra
slots lose every select_k exactly like build padding does.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import env as _env
from raft_tpu.core.logger import logger as _log
from raft_tpu.store.budget import MemoryBudget, default_budget
from raft_tpu.store.pagestore import PageStore
from raft_tpu.store.tiered import TieredStore

__all__ = [
    "PagedLists",
    "PagedRows",
    "gather_lists",
    "pages_for_lists",
    "paginate_index",
    "default_page_rows",
]

#: backends paginate_index understands (module basename of the Index type)
PAGED_KINDS = ("ivf_flat", "ivf_pq", "brute_force", "cagra")


def default_page_rows() -> int:
    return int(_env.env_int("RAFT_TPU_PAGE_ROWS", 1024))


class PagedLists:
    """Device view of a paged ``[L, cap, payload]`` padded-list tensor.

    Children: ``pool [slots, page_rows, payload]``, ``page_slot
    [L * pages_per_list] int32``.  ``shape`` / ``dtype`` mirror the
    monolithic tensor so call sites that read them stay untouched.
    """

    def __init__(self, pool, page_slot, pages_per_list: int):
        self.pool = pool
        self.page_slot = page_slot
        self.pages_per_list = int(pages_per_list)

    @property
    def shape(self):
        ppl = self.pages_per_list
        return (
            self.page_slot.shape[0] // ppl,
            ppl * self.pool.shape[1],
        ) + tuple(self.pool.shape[2:])

    @property
    def dtype(self):
        return self.pool.dtype

    @property
    def page_rows(self) -> int:
        return self.pool.shape[1]

    def tree_flatten(self):
        return (self.pool, self.page_slot), (self.pages_per_list,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        obj = object.__new__(cls)
        obj.pool, obj.page_slot = children
        obj.pages_per_list = aux[0]
        return obj


class PagedRows:
    """Device view of a paged flat row matrix ``[n, d]`` with a
    ``decode(ids) -> f32 rows`` page-table gather (cagra's
    ``_gather_rows`` contract for non-dense datasets)."""

    def __init__(self, pool, page_slot, n_rows: int):
        self.pool = pool
        self.page_slot = page_slot
        self.n_rows = int(n_rows)

    @property
    def shape(self):
        return (self.n_rows,) + tuple(self.pool.shape[2:])

    @property
    def dtype(self):
        return self.pool.dtype

    @property
    def page_rows(self) -> int:
        return self.pool.shape[1]

    def decode(self, ids):
        """Rows for ``ids`` (clipped like the dense gather), upcast f32."""
        pr = self.pool.shape[1]
        ids = jnp.clip(ids, 0, self.n_rows - 1)
        page = ids // pr
        return self.pool[self.page_slot[page], ids - page * pr].astype(
            jnp.float32
        )

    def tree_flatten(self):
        return (self.pool, self.page_slot), (self.n_rows,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        obj = object.__new__(cls)
        obj.pool, obj.page_slot = children
        obj.n_rows = aux[0]
        return obj


jax.tree_util.register_pytree_node(
    PagedLists, PagedLists.tree_flatten, PagedLists.tree_unflatten
)
jax.tree_util.register_pytree_node(
    PagedRows, PagedRows.tree_flatten, PagedRows.tree_unflatten
)


def gather_lists(list_data, pp):
    """``list_data[pp]`` with page-table indirection when paged.

    ``pp`` is any int array of list ids; the result appends
    ``(cap, payload...)`` to its shape, exactly like the monolithic
    gather.  Non-resident pages read through a wrapped slot index
    (in-bounds, garbage values) — callers uphold the residency contract
    via ``TieredStore.ensure_resident`` before dispatch, and padding
    probes are masked downstream by the ids/q2 invalid masks.
    """
    if isinstance(list_data, PagedLists):
        ppl = list_data.pages_per_list
        pages = pp[..., None] * ppl + jnp.arange(ppl, dtype=jnp.int32)
        rows = list_data.pool[list_data.page_slot[pages]]
        return rows.reshape(tuple(pp.shape) + tuple(list_data.shape[1:]))
    return list_data[pp]


def pages_for_lists(lists: np.ndarray, pages_per_list: int) -> np.ndarray:
    """The page ids covering ``lists`` (host-side prefetch keying)."""
    lists = np.asarray(lists, np.int64).reshape(-1)  # raft-tpu: ignore[HOSTSYNC] host-side page-id arithmetic on an already-host list set
    return (
        lists[:, None] * pages_per_list + np.arange(pages_per_list)
    ).ravel()


# -- pagination ---------------------------------------------------------------
def _kind_of(index) -> str:
    return type(index).__module__.rsplit(".", 1)[-1]


def _repad(arr: np.ndarray, cap2: int, fill) -> np.ndarray:
    """Grow axis 1 (list capacity) to ``cap2`` with ``fill``."""
    L, cap = arr.shape[:2]
    if cap == cap2:
        return arr
    out = np.full((L, cap2) + arr.shape[2:], fill, arr.dtype)
    out[:, :cap] = arr
    return out


def _paginate_lists(
    index, page_rows: int, name: str, budget: Optional[MemoryBudget],
    *, y2_attr: str, y2_fill,
) -> TieredStore:
    """Shared IVF pagination: page ``list_data``, repad the per-slot
    sidecars to the page-aligned capacity, alias the cold tier back as
    the monolithic host view."""
    ld = np.asarray(index.list_data)
    L, cap = ld.shape[:2]
    ppl = max(1, -(-cap // page_rows))
    cap2 = ppl * page_rows
    ld = _repad(ld, cap2, 0)
    li = _repad(np.asarray(index.list_index), cap2, -1)
    y2 = _repad(np.asarray(getattr(index, y2_attr)), cap2, y2_fill)

    store = PageStore(ld.reshape((L * cap2,) + ld.shape[2:]), page_rows)
    tiered = TieredStore(store, name=name, budget=budget)
    tiered.pages_per_list = ppl
    index.list_data = store.data.reshape((L, cap2) + ld.shape[2:])
    index.list_index = jnp.asarray(li)
    setattr(index, y2_attr, jnp.asarray(y2))
    index.paged = tiered
    return tiered


def _paginate_rows(
    index, rows: np.ndarray, page_rows: int, name: str,
    budget: Optional[MemoryBudget],
) -> TieredStore:
    store = PageStore(rows, page_rows)
    tiered = TieredStore(store, name=name, budget=budget)
    index.dataset = store.data[: rows.shape[0]]
    index.paged = tiered
    return tiered


def paginate_index(
    index,
    *,
    page_rows: Optional[int] = None,
    budget: Optional[MemoryBudget] = "default",  # type: ignore[assignment]
    name: str = "index",
) -> TieredStore:
    """Convert a built backend index to paged storage in place.

    The payload tensor moves to host pages (cold tier, authoritative —
    save/compaction decode paths read it unchanged) behind a
    budget-sized HBM hot pool at ``index.paged``.  Idempotent.

    brute_force/cagra scan arbitrary rows per dispatch, so their whole
    payload must fit the hot pool (identity-pinned / fully resident at
    first search; ``BudgetExceeded`` otherwise).  The IVF backends scan
    only the coarse-probed lists' pages and serve payloads larger than
    the hot pool.
    """
    if getattr(index, "paged", None) is not None:
        return index.paged
    kind = _kind_of(index)
    if kind not in PAGED_KINDS:
        raise ValueError(
            f"paginate_index: unsupported index kind {kind!r} "
            f"(supported: {PAGED_KINDS})"
        )
    pr = int(page_rows) if page_rows else default_page_rows()
    if pr < 8 or pr % 8:
        raise ValueError(
            f"page_rows must be a positive multiple of 8 (TPU sublane), "
            f"got {pr}"
        )
    if budget == "default":
        budget = default_budget()

    if kind == "ivf_flat":
        tiered = _paginate_lists(
            index, pr, name, budget, y2_attr="list_norms", y2_fill=np.inf
        )
    elif kind == "ivf_pq":
        ld = np.asarray(index.list_data)
        cap = ld.shape[1]
        ppl = max(1, -(-cap // pr))
        # codes ride the cold tier only: they are not on the scan path
        # (the decoded list_data cache is) — host numpy keeps HBM clean
        index.list_codes = _repad(np.asarray(index.list_codes), ppl * pr, 0)
        tiered = _paginate_lists(
            index, pr, name, budget, y2_attr="list_y2", y2_fill=0.0
        )
    else:  # brute_force / cagra: flat dataset rows
        ds = getattr(index, "dataset", None)
        if ds is None or getattr(ds, "ndim", 0) != 2:
            raise ValueError(
                f"paginate_index: {kind} index has no dense [n, d] dataset "
                "to page (VPQ/dataset-free indexes stay monolithic)"
            )
        tiered = _paginate_rows(index, np.asarray(ds), pr, name, budget)
    _log.debug(
        "paginate_index: kind=%s name=%s pages=%d page_rows=%d slots=%d",
        kind, name, tiered.n_pages, pr, tiered.slots,
    )
    return tiered
