"""Hard memory-budget accounting for the paged store.

"Memory Safe Computations with XLA Compiler" (PAPERS.md) makes the
memory bound a first-class constraint the compiler must respect instead
of an observed-after-the-fact gauge.  This module is the serving-side
equivalent: a :class:`MemoryBudget` is a process-wide ledger of HBM
bytes *reserved* by named owners (one per :class:`~raft_tpu.store.
tiered.TieredStore` hot pool, plus the compactor's projected rebuild
peak), and every reservation either fits or raises a loud
:class:`BudgetExceeded` — never an opaque device OOM mid-dispatch.

The default budget comes from ``RAFT_TPU_PAGE_HBM_BUDGET_MB``; unset
means "no budget" (``default_budget()`` returns ``None``) and the paged
store sizes its hot pool to hold every page, which preserves the
monolithic path's behavior exactly.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from raft_tpu.core import env as _env

__all__ = [
    "BudgetExceeded",
    "MemoryBudget",
    "default_budget",
    "set_default_budget",
]


class BudgetExceeded(RuntimeError):
    """A reservation (or residency request) does not fit the budget.

    Raised instead of letting the allocation proceed toward a device
    OOM — the message carries the ledger snapshot so the operator sees
    *which* owners hold the budget, not just that it ran out.
    """


class MemoryBudget:
    """Thread-safe byte ledger with hard admission.

    ``reserve`` is the only growing operation and it is all-or-nothing:
    the ledger never over-commits, so a successful reservation is a
    guarantee the bytes were inside the limit at grant time.
    """

    def __init__(self, limit_bytes: int):
        if limit_bytes <= 0:
            raise ValueError(f"limit_bytes must be positive, got {limit_bytes}")
        self.limit_bytes = int(limit_bytes)
        self._lock = threading.Lock()
        self._owners: Dict[str, int] = {}

    # -- ledger ops ----------------------------------------------------------
    def reserve(self, owner: str, nbytes: int) -> None:
        """Grow ``owner``'s reservation by ``nbytes`` or raise."""
        nbytes = int(nbytes)
        if nbytes < 0:
            raise ValueError(f"nbytes must be non-negative, got {nbytes}")
        with self._lock:
            used = sum(self._owners.values())
            if used + nbytes > self.limit_bytes:
                raise BudgetExceeded(
                    f"memory budget exceeded: {owner!r} requested {nbytes}B "
                    f"with {self.limit_bytes - used}B of {self.limit_bytes}B "
                    f"remaining (owners: {dict(self._owners)})"
                )
            self._owners[owner] = self._owners.get(owner, 0) + nbytes

    def release(self, owner: str, nbytes: Optional[int] = None) -> None:
        """Shrink ``owner``'s reservation (all of it when ``nbytes`` is
        ``None``).  Releasing an unknown owner is a no-op — weakref
        finalizers may fire after an explicit release."""
        with self._lock:
            held = self._owners.get(owner)
            if held is None:
                return
            if nbytes is None or nbytes >= held:
                del self._owners[owner]
            else:
                self._owners[owner] = held - int(nbytes)

    # -- queries -------------------------------------------------------------
    def would_fit(self, nbytes: int) -> bool:
        """Whether a new ``nbytes`` reservation would be granted now."""
        with self._lock:
            return sum(self._owners.values()) + int(nbytes) <= self.limit_bytes

    def reserved(self) -> int:
        with self._lock:
            return sum(self._owners.values())

    def remaining(self) -> int:
        with self._lock:
            return max(0, self.limit_bytes - sum(self._owners.values()))

    def snapshot(self) -> Dict[str, object]:
        """JSON-safe ledger state for ``healthz()`` / stats surfaces."""
        with self._lock:
            used = sum(self._owners.values())
            return {
                "limit_bytes": self.limit_bytes,
                "reserved_bytes": used,
                "remaining_bytes": max(0, self.limit_bytes - used),
                "utilization": used / self.limit_bytes,
                "owners": dict(self._owners),
            }


_UNSET = object()
_default = _UNSET
_default_lock = threading.Lock()


def default_budget() -> Optional[MemoryBudget]:
    """The process budget from ``RAFT_TPU_PAGE_HBM_BUDGET_MB`` (``None``
    when unset).  Created once on first read so reservations accumulate
    on one ledger; tests swap it with :func:`set_default_budget`."""
    global _default
    with _default_lock:
        if _default is _UNSET:
            mb = _env.env_int("RAFT_TPU_PAGE_HBM_BUDGET_MB")
            _default = MemoryBudget(mb << 20) if mb else None
        return _default


def set_default_budget(
    budget: Optional[MemoryBudget],
) -> Optional[MemoryBudget]:
    """Replace the process budget; returns the previous one.  Pass
    ``None`` to clear; the next ``default_budget()`` after a clear
    re-reads the environment only if the sentinel is restored via
    ``set_default_budget(_UNSET)``-style test fixtures — in practice
    tests set an explicit budget and restore the captured previous."""
    global _default
    with _default_lock:
        prev = None if _default is _UNSET else _default
        _default = budget
        return prev
