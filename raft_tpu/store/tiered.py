"""Two-tier page residency: HBM hot pool over host cold pages.

The hot tier is ONE static device array ``pool [slots, page_rows, ...]``
plus a device page table ``page_slot [n_pages] int32`` (−1 = not
resident).  Page movement rewrites *values* through two shape-bucketed
jitted scatters — shapes never change, so a warmed serving process pays
zero recompiles no matter how pages migrate (the recompile-tier
discipline of the padded-list layout, extended to residency).

Residency is demand-driven and clock-evicted:

- :meth:`ensure_resident` — blocking admission: the caller's pages are
  resident when it returns (search dispatch calls it with the pages of
  the coarse-probed lists).  Counts prefetch hits/misses.
- :meth:`prefetch` — async warm-start: a bounded daemon queue
  (``RAFT_TPU_PAGE_PREFETCH_DEPTH``) fetches pages off the caller's
  thread; a full queue drops the hint (prefetch is advisory).
- :meth:`evict` — clock (second-chance) victim selection over slots;
  runs implicitly when admission needs room.  An evict-then-refetch
  inside the thrash window publishes a rate-limited ``page_thrash``
  bus event — the operator signal that the hot pool is undersized.

Snapshot isolation rides jax's functional updates: a view captured via
:meth:`view` references the pool buffers of that moment; later fetches
build *new* arrays (no donation), so in-flight searches never observe a
page swap mid-dispatch.
"""

from __future__ import annotations

import queue
import threading
import time
import uuid
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import env as _env
from raft_tpu.core.logger import logger as _log
from raft_tpu.core.trace import traced
from raft_tpu.store.budget import BudgetExceeded, MemoryBudget
from raft_tpu.store.pagestore import PageStore

__all__ = ["TieredStore"]

#: fetches within this many admissions of the eviction count as thrash
_THRASH_WINDOW = 256
#: minimum seconds between page_thrash events per store
_THRASH_DEBOUNCE_S = 5.0


@jax.jit
def _pool_write(pool, slots, rows):
    """Scatter fetched pages into their slots (functional: new pool)."""
    return pool.at[slots].set(rows)


@jax.jit
def _slot_write(page_slot, pages, slots):
    """Rewrite page→slot entries (evictions ride as −1 values)."""
    return page_slot.at[pages].set(slots)


def _pow2(n: int) -> int:
    """Fetch-batch shape bucket: power of two ≥ n (bounds the distinct
    scatter shapes at O(log n_pages) executables)."""
    b = 1
    while b < n:
        b <<= 1
    return b


class TieredStore:
    """HBM hot pool + host cold tier over one :class:`PageStore`."""

    def __init__(
        self,
        store: PageStore,
        *,
        name: str = "index",
        budget: Optional[MemoryBudget] = None,
        max_slots: Optional[int] = None,
        prefetch_depth: Optional[int] = None,
    ):
        self.store = store
        self.name = name
        self.page_rows = store.page_rows
        n_pages = store.n_pages
        page_bytes = store.page_bytes
        slots = n_pages if max_slots is None else min(n_pages, int(max_slots))

        self._budget = budget
        self._budget_key = f"pager:{name}:{uuid.uuid4().hex[:8]}"
        if budget is not None:
            # size the pool to what the budget grants (hard admission):
            # page_slot + pool bytes charge the ledger together
            affordable = (budget.remaining() - 4 * n_pages) // max(page_bytes, 1)
            slots = min(slots, int(affordable))
            if slots < 1:
                raise BudgetExceeded(
                    f"pager {name!r}: budget cannot hold a single "
                    f"{page_bytes}B page (remaining "
                    f"{budget.remaining()}B of {budget.limit_bytes}B)"
                )
            budget.reserve(self._budget_key, slots * page_bytes + 4 * n_pages)
            # release on GC so a dropped index returns its budget even
            # without an explicit close()
            self._finalizer = weakref.finalize(
                self, budget.release, self._budget_key
            )
        self.slots = slots

        payload = store.pages.shape[2:]
        self.pool = jnp.zeros((slots, store.page_rows) + payload, store.dtype)
        self.page_slot = jnp.full((n_pages,), -1, jnp.int32)

        # host mirrors (the device arrays are never read back)
        self._resident = np.full(n_pages, -1, np.int32)   # page -> slot
        self._slot_page = np.full(slots, -1, np.int32)    # slot -> page
        self._ref = np.zeros(slots, bool)                 # clock ref bits
        self._hand = 0
        self._free = list(range(slots))
        self._pinned = False
        self._lock = threading.RLock()

        # counters (mirrored into the obs registry on every bump)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.prefetched = 0
        self.thrash = 0
        self._fetch_seq = 0
        self._evicted_at: Dict[int, int] = {}
        self._last_thrash_t = -1e9

        depth = prefetch_depth
        if depth is None:
            depth = _env.env_int("RAFT_TPU_PAGE_PREFETCH_DEPTH", 2)
        self._prefetch_q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._prefetch_thread: Optional[threading.Thread] = None

    # -- sizing --------------------------------------------------------------
    @property
    def n_pages(self) -> int:
        return self.store.n_pages

    @property
    def resident_count(self) -> int:
        with self._lock:
            return int((self._resident >= 0).sum())

    @property
    def nbytes(self) -> int:
        """Device bytes of the hot tier (pool + device page table)."""
        return int(self.pool.nbytes) + int(self.page_slot.nbytes)

    def close(self) -> None:
        """Release the budget reservation early (idempotent)."""
        if self._budget is not None:
            self._budget.release(self._budget_key)

    # -- residency -----------------------------------------------------------
    def _normalize(self, pages) -> np.ndarray:
        arr = np.unique(np.asarray(pages, np.int64).ravel())
        return arr[(arr >= 0) & (arr < self.n_pages)]

    @traced("store.pager.ensure")
    def ensure_resident(self, pages: Sequence[int]) -> None:
        """Blocking admission: every listed page is resident on return.

        Raises :class:`BudgetExceeded` when the request alone exceeds
        the hot pool — the loud alternative to thrashing every dispatch.
        """
        pages = self._normalize(pages)
        if pages.size == 0:
            return
        with self._lock:
            slot_of = self._resident[pages]
            present = slot_of >= 0
            hits = int(present.sum())
            missing = pages[~present]
            self.hits += hits
            if hits:
                self._ref[slot_of[present]] = True
                self._counter("raft_tpu_page_hits_total", hits)
            if missing.size == 0:
                return
            if pages.size > self.slots:
                raise BudgetExceeded(
                    f"pager {self.name!r}: {pages.size} pages requested "
                    f"but the hot pool holds {self.slots} "
                    f"(page_rows={self.page_rows}); raise "
                    "RAFT_TPU_PAGE_HBM_BUDGET_MB or RAFT_TPU_PAGE_ROWS"
                )
            self.misses += missing.size
            self._counter("raft_tpu_page_misses_total", int(missing.size))
            # pages of THIS admission may not be victimized mid-batch —
            # the clock's second sweep would otherwise evict a page the
            # caller was just promised (ref bits only survive one wrap)
            protected = np.zeros(self.slots, bool)
            protected[slot_of[present]] = True
            self._fetch(missing, protected)

    @traced("store.pager.prefetch")
    def prefetch(self, pages: Sequence[int]) -> bool:
        """Async warm-start keyed by the coarse-probe result.  Returns
        whether the hint was accepted (a full queue drops it)."""
        pages = self._normalize(pages)
        if pages.size == 0:
            return True
        with self._lock:
            pages = pages[self._resident[pages] < 0]
        if pages.size == 0:
            return True
        self._ensure_worker()
        try:
            self._prefetch_q.put_nowait(pages)
            return True
        except queue.Full:
            return False

    @traced("store.pager.evict")
    def evict(self, count: int = 1) -> List[int]:
        """Clock-evict up to ``count`` pages; returns the evicted page
        ids.  Pinned stores refuse (their views alias slot order)."""
        with self._lock:
            if self._pinned:
                raise RuntimeError(
                    f"pager {self.name!r} is pinned (identity placement); "
                    "eviction would corrupt aliased views"
                )
            evicted: List[int] = []
            occupied = int((self._slot_page >= 0).sum())
            for _ in range(min(count, occupied)):
                slot = self._clock_victim()
                if slot is None:
                    break
                evicted.append(self._evict_slot(slot))
                self._free.append(slot)
            if evicted:
                self._flush_slot_writes(
                    np.asarray(evicted, np.int64),
                    np.full(len(evicted), -1, np.int32),
                )
            return evicted

    def pin_identity(self) -> None:
        """Upload every page into its identity slot (slot i holds page
        i) in one transfer.  After pinning, ``pool.reshape(-1, ...)`` is
        bitwise the padded flat host array — the zero-overhead placement
        brute_force/cagra views rely on.  Requires a full-size pool."""
        with self._lock:
            if self._pinned:
                return
            if self.slots < self.n_pages:
                raise BudgetExceeded(
                    f"pager {self.name!r}: identity pinning needs "
                    f"{self.n_pages} slots, pool holds {self.slots}; this "
                    "backend requires the whole payload resident — raise "
                    "RAFT_TPU_PAGE_HBM_BUDGET_MB"
                )
            self.misses += self.n_pages
            self._counter("raft_tpu_page_misses_total", self.n_pages)
            self.pool = jnp.asarray(self.store.pages[self.store.page_table])
            self.page_slot = jnp.arange(self.n_pages, dtype=jnp.int32)
            self._resident = np.arange(self.n_pages, dtype=np.int32)
            self._slot_page = np.arange(self.slots, dtype=np.int32)
            self._ref[:] = True
            self._free = []
            self._pinned = True

    def view(self) -> Tuple[jax.Array, jax.Array]:
        """Snapshot of (pool, page_slot) — consistent by construction
        (both are replaced together under the lock)."""
        with self._lock:
            return self.pool, self.page_slot

    def counters(self) -> Tuple[int, int, int]:
        """One consistent ``(hits, misses, resident)`` read — the
        per-request page attribution brackets a dispatch's pager calls
        with two of these and reports the deltas (explain plans)."""
        with self._lock:
            return (
                self.hits, self.misses, int((self._resident >= 0).sum())
            )

    def resident_pages(self) -> np.ndarray:
        """Resident page ids ordered by slot (serialization: replaying
        ``ensure_resident`` over this restores the placement)."""
        with self._lock:
            order = np.argsort(self._resident[self._resident >= 0])
            pages = np.flatnonzero(self._resident >= 0).astype(np.int32)
            return pages[order]

    def stats(self) -> Dict[str, object]:
        with self._lock:
            resident = int((self._resident >= 0).sum())
            return {
                "name": self.name,
                "n_pages": self.n_pages,
                "slots": self.slots,
                "page_rows": self.page_rows,
                "resident": resident,
                "host_only": self.n_pages - resident,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "prefetched": self.prefetched,
                "thrash": self.thrash,
                "pinned": self._pinned,
                "hot_bytes": self.nbytes,
                "cold_bytes": self.store.nbytes,
            }

    # -- internals (lock held) -----------------------------------------------
    def _fetch(
        self, missing: np.ndarray, protected: Optional[np.ndarray] = None
    ) -> None:
        """Admit ``missing`` pages (none currently resident).
        ``protected`` slots (the admission's hit pages) are never
        victimized; slots claimed here join the protected set."""
        if protected is None:
            protected = np.zeros(self.slots, bool)
        slots = np.empty(missing.size, np.int32)
        evicted: List[int] = []
        for i, page in enumerate(missing):
            if self._free:
                slot = self._free.pop()
            else:
                slot = self._clock_victim(protected)
                if slot is None:  # pragma: no cover - guarded by caller
                    raise BudgetExceeded(
                        f"pager {self.name!r}: no evictable slot "
                        f"(slots={self.slots})"
                    )
                evicted.append(self._evict_slot(slot))
            slots[i] = slot
            protected[slot] = True
            self._slot_page[slot] = page
            self._resident[page] = slot
            self._ref[slot] = True
        self._fetch_seq += missing.size
        self._note_thrash(missing)

        rows = self.store.gather(missing)
        B = _pow2(missing.size)
        pad = B - missing.size
        if pad:
            # duplicate scatter indices writing identical values are a
            # well-defined no-op — padding repeats the first entry
            slots = np.concatenate([slots, np.repeat(slots[:1], pad)])
            rows = np.concatenate([rows, np.repeat(rows[:1], pad, axis=0)])
        self.pool = _pool_write(  # raft-tpu: ignore[LOCKORDER] every caller (ensure_resident / _prefetch_loop) holds self._lock
            self.pool, jnp.asarray(slots), jnp.asarray(rows)
        )
        idx = np.concatenate([np.asarray(evicted, np.int64), missing])
        val = np.concatenate(
            [np.full(len(evicted), -1, np.int32), slots[: missing.size]]
        )
        self._flush_slot_writes(idx, val)

    def _flush_slot_writes(self, pages: np.ndarray, slots: np.ndarray) -> None:
        B = _pow2(max(1, pages.size))
        pad = B - pages.size
        if pad:
            pages = np.concatenate([pages, np.repeat(pages[:1], pad)])
            slots = np.concatenate([slots, np.repeat(slots[:1], pad)])
        self.page_slot = _slot_write(  # raft-tpu: ignore[LOCKORDER] callers (_fetch / evict) hold self._lock
            self.page_slot,
            jnp.asarray(pages, jnp.int32),
            jnp.asarray(slots, jnp.int32),
        )

    def _clock_victim(
        self, protected: Optional[np.ndarray] = None
    ) -> Optional[int]:
        """Second-chance sweep: clear ref bits until an unreferenced,
        unprotected occupied slot comes around."""
        for _ in range(3 * self.slots):
            slot = self._hand
            self._hand = (self._hand + 1) % self.slots
            if self._slot_page[slot] < 0:
                continue
            if protected is not None and protected[slot]:
                continue
            if self._ref[slot]:
                self._ref[slot] = False
                continue
            return slot
        return None

    def _evict_slot(self, slot: int) -> int:
        page = int(self._slot_page[slot])
        self._slot_page[slot] = -1
        self._resident[page] = -1
        self._ref[slot] = False
        self._evicted_at[page] = self._fetch_seq
        self.evictions += 1
        self._counter("raft_tpu_page_evictions_total", 1)
        return page

    def _note_thrash(self, fetched: np.ndarray) -> None:
        """Evict-then-refetch inside the window = the pool is too small
        for the working set; publish (debounced) so it lands in the
        incident stream instead of only a counter."""
        n = 0
        for page in fetched:
            seq = self._evicted_at.pop(int(page), None)
            if seq is not None and self._fetch_seq - seq <= _THRASH_WINDOW:
                n += 1
        if not n:
            return
        self.thrash += n
        now = time.monotonic()
        if now - self._last_thrash_t < _THRASH_DEBOUNCE_S:
            return
        self._last_thrash_t = now
        try:
            from raft_tpu.obs import events as _events

            _events.publish(
                "page_thrash",
                f"pager {self.name!r}: {n} pages refetched within "
                f"{_THRASH_WINDOW} admissions of eviction "
                f"(slots={self.slots}, pages={self.n_pages})",
                index=self.name,
                pages=int(n),
                slots=int(self.slots),
                n_pages=int(self.n_pages),
            )
        except Exception:  # pragma: no cover - obs must never break serving
            _log.debug("page_thrash publish failed", exc_info=True)

    def _counter(self, name: str, value: int) -> None:
        try:
            from raft_tpu.obs import registry as _registry

            _registry.default_registry().counter(name).inc(
                float(value), index=self.name
            )
        except Exception:  # pragma: no cover
            pass

    # -- async prefetch ------------------------------------------------------
    def _ensure_worker(self) -> None:
        if self._prefetch_thread is not None and self._prefetch_thread.is_alive():
            return
        t = threading.Thread(
            target=self._prefetch_loop,
            name=f"raft-tpu-pager-{self.name}",
            daemon=True,
        )
        self._prefetch_thread = t
        t.start()

    def _prefetch_loop(self) -> None:
        while True:
            pages = self._prefetch_q.get()
            try:
                with self._lock:
                    missing = pages[self._resident[pages] < 0]
                    if missing.size and missing.size <= self.slots:
                        self._fetch(missing)
                        self.prefetched += missing.size
            except Exception:  # pragma: no cover - advisory path
                _log.debug("async prefetch failed", exc_info=True)
            finally:
                self._prefetch_q.task_done()
