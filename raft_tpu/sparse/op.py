"""Sparse structure ops: sort, dedupe, filter, row slicing
(ref: sparse/op/{sort,reduce,filter,row_op,slice}.cuh)."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.sparse.formats import COO, CSR, coo_order


def sort_coo(coo: COO) -> COO:
    """Row-major sort (ref: sparse/op/sort.cuh coo_sort)."""
    return coo.sorted_by_row()


def max_duplicates(coo: COO) -> COO:
    """Sum coincident (i, j) entries and compact (ref: sparse/op/reduce.cuh
    max_duplicates — the reference keeps max; we expose both)."""
    return _reduce_duplicates(coo, "max")


def sum_duplicates(coo: COO) -> COO:
    return _reduce_duplicates(coo, "add")


def _reduce_duplicates(coo: COO, op: str) -> COO:
    """Row-major sort, aggregate coincident (i, j) groups with ``op``
    (add/mean/max/min), compact. Forces a host sync for the new nnz — like
    every structure-mutating op on the fixed-capacity containers (and like
    the reference, which syncs its stream to size the output)."""
    n = coo.shape[0]
    order = coo_order(coo.rows, coo.cols, coo.valid, n)
    rows, cols, data, valid = (
        coo.rows[order], coo.cols[order], coo.data[order], coo.valid[order]
    )
    first = jnp.concatenate(
        [jnp.ones(1, bool),
         (rows[1:] != rows[:-1]) | (cols[1:] != cols[:-1]) | ~valid[1:]]
    )
    seg = jnp.cumsum(first) - 1
    m = rows.shape[0]
    if op in ("add", "mean"):
        agg = jax.ops.segment_sum(jnp.where(valid, data, 0), seg, num_segments=m)
        if op == "mean":
            cnt = jax.ops.segment_sum(
                jnp.where(valid, 1.0, 0.0), seg, num_segments=m
            )
            agg = agg / jnp.maximum(cnt, 1.0)
    elif op == "max":
        agg = jax.ops.segment_max(
            jnp.where(valid, data, -jnp.inf), seg, num_segments=m
        )
    elif op == "min":
        agg = jax.ops.segment_min(
            jnp.where(valid, data, jnp.inf), seg, num_segments=m
        )
    else:
        raise ValueError(f"unknown reduce op {op}")
    keep = first & valid
    order2 = jnp.argsort(~keep, stable=True)
    nnz = int(jnp.sum(keep))
    return COO(
        jnp.where(keep, rows, n)[order2],
        jnp.where(keep, cols, 0)[order2],
        jnp.where(keep, agg[seg], 0)[order2],
        coo.shape,
        nnz,
    )


def filter_values(coo: COO, *, threshold: float) -> COO:
    """Drop entries with |value| ≤ threshold (ref: sparse/op/filter.cuh
    coo_remove_scalar). Capacity is kept; padding grows."""
    keep = coo.valid & (jnp.abs(coo.data) > threshold)
    order = jnp.argsort(~keep, stable=True)
    nnz = int(jnp.sum(keep))
    n = coo.shape[0]
    return COO(
        jnp.where(keep, coo.rows, n)[order],
        jnp.where(keep, coo.cols, 0)[order],
        jnp.where(keep, coo.data, 0)[order],
        coo.shape,
        nnz,
    )


def filter_degree(coo: COO, *, min_degree: int) -> COO:
    """Drop all entries of rows with degree < min_degree
    (ref: sparse/op/filter.cuh remove low-degree rows)."""
    n = coo.shape[0]
    deg = jnp.zeros(n, jnp.int32).at[
        jnp.where(coo.valid, coo.rows, n)
    ].add(jnp.where(coo.valid, 1, 0), mode="drop")
    keep = coo.valid & (deg[jnp.clip(coo.rows, 0, n - 1)] >= min_degree)
    order = jnp.argsort(~keep, stable=True)
    nnz = int(jnp.sum(keep))
    return COO(
        jnp.where(keep, coo.rows, n)[order],
        jnp.where(keep, coo.cols, 0)[order],
        jnp.where(keep, coo.data, 0)[order],
        coo.shape,
        nnz,
    )


def slice_rows(csr: CSR, start: int, stop: int) -> CSR:
    """Contiguous row-range view → compacted CSR (ref: sparse/op/slice.cuh).
    Host-side compaction (capacity changes)."""
    indptr = np.asarray(csr.indptr)
    indices = np.asarray(csr.indices)
    data = np.asarray(csr.data)
    lo, hi = int(indptr[start]), int(indptr[stop])
    new_ptr = indptr[start : stop + 1] - lo
    return CSR(new_ptr, indices[lo:hi], data[lo:hi], (stop - start, csr.shape[1]))


def row_op(csr: CSR, fn) -> CSR:
    """Apply fn(row_id, values) per slot (ref: sparse/op/row_op.cuh csr_row_op).
    fn maps ([cap] rows, [cap] data) → [cap] data."""
    rows = csr.row_ids()
    data = jnp.where(csr.valid, fn(rows, csr.data), 0)
    return CSR(csr.indptr, csr.indices, data, csr.shape, csr.nnz)
