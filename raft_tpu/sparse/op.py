"""Sparse structure ops: sort, dedupe, filter, row slicing
(ref: sparse/op/{sort,reduce,filter,row_op,slice}.cuh)."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.sparse.formats import COO, CSR, coo_order


def sort_coo(coo: COO) -> COO:
    """Row-major sort (ref: sparse/op/sort.cuh coo_sort)."""
    return coo.sorted_by_row()


def max_duplicates(coo: COO) -> COO:
    """Sum coincident (i, j) entries and compact (ref: sparse/op/reduce.cuh
    max_duplicates — the reference keeps max; we expose both)."""
    return _reduce_duplicates(coo, "max")


def sum_duplicates(coo: COO) -> COO:
    return _reduce_duplicates(coo, "add")


def _reduce_duplicates(coo: COO, op: str) -> COO:
    """Row-major sort, aggregate coincident (i, j) groups with ``op``
    (add/mean/max/min), compact. Forces a host sync for the new nnz — like
    every structure-mutating op on the fixed-capacity containers (and like
    the reference, which syncs its stream to size the output)."""
    n = coo.shape[0]
    order = coo_order(coo.rows, coo.cols, coo.valid, n)
    rows, cols, data, valid = (
        coo.rows[order], coo.cols[order], coo.data[order], coo.valid[order]
    )
    first = jnp.concatenate(
        [jnp.ones(1, bool),
         (rows[1:] != rows[:-1]) | (cols[1:] != cols[:-1]) | ~valid[1:]]
    )
    seg = jnp.cumsum(first) - 1
    m = rows.shape[0]
    if op in ("add", "mean"):
        agg = jax.ops.segment_sum(jnp.where(valid, data, 0), seg, num_segments=m)
        if op == "mean":
            cnt = jax.ops.segment_sum(
                jnp.where(valid, 1.0, 0.0), seg, num_segments=m
            )
            agg = agg / jnp.maximum(cnt, 1.0)
    elif op == "max":
        agg = jax.ops.segment_max(
            jnp.where(valid, data, -jnp.inf), seg, num_segments=m
        )
    elif op == "min":
        agg = jax.ops.segment_min(
            jnp.where(valid, data, jnp.inf), seg, num_segments=m
        )
    else:
        raise ValueError(f"unknown reduce op {op}")
    keep = first & valid
    order2 = jnp.argsort(~keep, stable=True)
    nnz = int(jnp.sum(keep))
    return COO(
        jnp.where(keep, rows, n)[order2],
        jnp.where(keep, cols, 0)[order2],
        jnp.where(keep, agg[seg], 0)[order2],
        coo.shape,
        nnz,
    )


def filter_values(coo: COO, *, threshold: float) -> COO:
    """Drop entries with |value| ≤ threshold (ref: sparse/op/filter.cuh
    coo_remove_scalar). Capacity is kept; padding grows."""
    keep = coo.valid & (jnp.abs(coo.data) > threshold)
    order = jnp.argsort(~keep, stable=True)
    nnz = int(jnp.sum(keep))
    n = coo.shape[0]
    return COO(
        jnp.where(keep, coo.rows, n)[order],
        jnp.where(keep, coo.cols, 0)[order],
        jnp.where(keep, coo.data, 0)[order],
        coo.shape,
        nnz,
    )


def filter_degree(coo: COO, *, min_degree: int) -> COO:
    """Drop all entries of rows with degree < min_degree
    (ref: sparse/op/filter.cuh remove low-degree rows)."""
    n = coo.shape[0]
    deg = jnp.zeros(n, jnp.int32).at[
        jnp.where(coo.valid, coo.rows, n)
    ].add(jnp.where(coo.valid, 1, 0), mode="drop")
    keep = coo.valid & (deg[jnp.clip(coo.rows, 0, n - 1)] >= min_degree)
    order = jnp.argsort(~keep, stable=True)
    nnz = int(jnp.sum(keep))
    return COO(
        jnp.where(keep, coo.rows, n)[order],
        jnp.where(keep, coo.cols, 0)[order],
        jnp.where(keep, coo.data, 0)[order],
        coo.shape,
        nnz,
    )


def slice_rows(csr: CSR, start: int, stop: int) -> CSR:
    """Contiguous row-range view → compacted CSR (ref: sparse/op/slice.cuh).
    Host-side compaction (capacity changes)."""
    indptr = np.asarray(csr.indptr)
    indices = np.asarray(csr.indices)
    data = np.asarray(csr.data)
    lo, hi = int(indptr[start]), int(indptr[stop])
    new_ptr = indptr[start : stop + 1] - lo
    return CSR(new_ptr, indices[lo:hi], data[lo:hi], (stop - start, csr.shape[1]))


def row_op(csr: CSR, fn) -> CSR:
    """Apply fn(row_id, values) per slot (ref: sparse/op/row_op.cuh csr_row_op).
    fn maps ([cap] rows, [cap] data) → [cap] data."""
    rows = csr.row_ids()
    data = jnp.where(csr.valid, fn(rows, csr.data), 0)
    return CSR(csr.indptr, csr.indices, data, csr.shape, csr.nnz)


def select_k(csr: CSR, k: int, *, select_min: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Per-row top-k over a CSR matrix's stored values
    (ref: sparse/matrix/select_k.cuh — batched select over sparse rows).

    Returns (values [n_rows, k], col_ids [n_rows, k]); rows with fewer than
    k stored entries pad with ±inf / -1. Static-shape formulation: two
    stable sorts over the slot axis (value, then row) give per-row ranks,
    then one scatter — no per-row dynamic loops.
    """
    n_rows = csr.shape[0]
    rows = csr.row_ids()                       # padding slots → n_rows
    vals = csr.data.astype(jnp.float32)
    worst = jnp.inf if select_min else -jnp.inf
    vals = jnp.where(csr.valid, vals, worst)
    # sort slots by value (best first), then stable by row: slots end up
    # grouped by row in selection order, padding after real slots
    key_vals = vals if select_min else -vals
    order1 = jnp.argsort(key_vals, stable=True)
    order2 = jnp.argsort(rows[order1], stable=True)
    order = order1[order2]
    sorted_rows = rows[order]
    # within-row rank = position − first position of that row; row starts
    # are exactly indptr (indptr[0] == 0 per the CSR contract)
    starts = csr.indptr
    pos = jnp.arange(csr.cap)
    rank = pos - starts[jnp.clip(sorted_rows, 0, n_rows)]
    keep = (sorted_rows < n_rows) & (rank < k)
    out_v = jnp.full((n_rows + 1, k), worst, jnp.float32)
    out_i = jnp.full((n_rows + 1, k), -1, jnp.int32)
    r = jnp.where(keep, sorted_rows, n_rows)
    c = jnp.clip(rank, 0, k - 1)
    out_v = out_v.at[r, c].set(vals[order], mode="drop")
    out_i = out_i.at[r, c].set(csr.indices[order], mode="drop")
    return out_v[:n_rows], out_i[:n_rows]
