"""Format conversions (ref: sparse/convert/{coo,csr,dense}.cuh)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.sparse.formats import COO, CSR


def coo_to_csr(coo: COO) -> CSR:
    """Sorted COO → CSR (ref: sparse/convert/csr.cuh sorted_coo_to_csr)."""
    s = coo.sorted_by_row()
    n_rows = coo.shape[0]
    counts = jnp.zeros(n_rows, jnp.int32).at[
        jnp.where(s.valid, s.rows, n_rows)
    ].add(jnp.where(s.valid, 1, 0), mode="drop")
    indptr = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts).astype(jnp.int32)]
    )
    return CSR(indptr, s.cols, jnp.where(s.valid, s.data, 0), coo.shape, coo.nnz)


def csr_to_coo(csr: CSR) -> COO:
    """CSR → COO row expansion (ref: sparse/convert/coo.cuh csr_to_coo)."""
    return COO(csr.row_ids(), csr.indices, csr.data, csr.shape, csr.nnz)


def dense_to_csr(m: jax.Array, *, tol: float = 0.0) -> CSR:
    """(ref: sparse/convert/csr.cuh dense_to_csr; host nnz discovery)"""
    return CSR.from_dense(m, tol=tol)


def dense_to_coo(m: jax.Array, *, tol: float = 0.0) -> COO:
    return COO.from_dense(m, tol=tol)


def csr_to_dense(csr: CSR) -> jax.Array:
    """(ref: sparse/convert/dense.cuh)"""
    return csr.to_dense()


def coo_to_dense(coo: COO) -> jax.Array:
    return coo.to_dense()
