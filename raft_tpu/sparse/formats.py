"""Sparse matrix containers: COO and CSR.

Reference: ``sparse/coo.hpp``, ``sparse/csr.hpp`` and the owning/view types in
``core/{coo_matrix,csr_matrix,device_coo_matrix,device_csr_matrix}.hpp``
(SURVEY §2.1, §2.6).

TPU re-design: XLA requires static shapes, so a sparse container carries a
*fixed capacity* of slots with an explicit valid count ``nnz``; slots past
``nnz`` are padding (row = n_rows sentinel for COO padding, value 0). All
arrays live on device as jnp arrays; both types are registered pytrees so
they pass through jit/vmap/scan. Structure-mutating ops (dedupe, filter)
produce new containers and are free to round-trip through host — exactly
where the reference synchronizes its stream to compute new nnz.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def coo_order(rows, cols, valid, n_rows):
    """Row-major (row, col) argsort with invalid slots last — composed from
    two stable int32 sorts, so no wide key is needed (int32-safe at any
    matrix size, unlike a rows*n_cols+cols key under disabled x64)."""
    order = jnp.argsort(cols, stable=True)
    r = jnp.where(valid, rows, n_rows)[order]
    return order[jnp.argsort(r, stable=True)]


@jax.tree_util.register_pytree_node_class
class COO:
    """Coordinate-format sparse matrix (ref: sparse/coo.hpp COO<T>).

    rows/cols: [cap] int32 (padding rows = n_rows, cols = 0)
    data:      [cap] float
    nnz:       python int ≤ cap (static)
    """

    def __init__(self, rows, cols, data, shape: Tuple[int, int], nnz=None):
        self.rows = jnp.asarray(rows, jnp.int32)
        self.cols = jnp.asarray(cols, jnp.int32)
        self.data = jnp.asarray(data)
        self.shape = tuple(shape)
        self.nnz = int(nnz) if nnz is not None else int(self.rows.shape[0])

    def tree_flatten(self):
        return (self.rows, self.cols, self.data), (self.shape, self.nnz)

    @classmethod
    def tree_unflatten(cls, aux, children):
        rows, cols, data = children
        return cls(rows, cols, data, aux[0], aux[1])

    @property
    def cap(self) -> int:
        return int(self.rows.shape[0])

    @property
    def valid(self) -> jax.Array:
        """[cap] bool mask of live slots."""
        return jnp.arange(self.cap) < self.nnz

    @classmethod
    def from_dense(cls, m, *, tol: float = 0.0) -> "COO":
        """Dense → COO (host-side nnz discovery; ref: sparse/convert/coo)."""
        m = np.asarray(m)
        r, c = np.nonzero(np.abs(m) > tol)
        return cls(r.astype(np.int32), c.astype(np.int32), m[r, c], m.shape)

    def to_dense(self) -> jax.Array:
        out = jnp.zeros(self.shape, self.data.dtype)
        v = self.valid
        r = jnp.where(v, self.rows, self.shape[0])  # padding → dropped row
        return out.at[r, self.cols].add(jnp.where(v, self.data, 0), mode="drop")

    def sorted_by_row(self) -> "COO":
        """Row-major (then col) ordering with padding pushed to the end."""
        order = coo_order(self.rows, self.cols, self.valid, self.shape[0])
        return COO(
            self.rows[order], self.cols[order], self.data[order], self.shape, self.nnz
        )


@jax.tree_util.register_pytree_node_class
class CSR:
    """Compressed-sparse-row matrix (ref: sparse/csr.hpp / core/csr_matrix.hpp).

    indptr:  [n_rows+1] int32 (indptr[n_rows] == nnz)
    indices: [cap] int32 column ids (padding = 0)
    data:    [cap] float (padding = 0)
    """

    def __init__(self, indptr, indices, data, shape: Tuple[int, int], nnz=None):
        self.indptr = jnp.asarray(indptr, jnp.int32)
        self.indices = jnp.asarray(indices, jnp.int32)
        self.data = jnp.asarray(data)
        self.shape = tuple(shape)
        self.nnz = int(nnz) if nnz is not None else int(self.indices.shape[0])

    def tree_flatten(self):
        return (self.indptr, self.indices, self.data), (self.shape, self.nnz)

    @classmethod
    def tree_unflatten(cls, aux, children):
        indptr, indices, data = children
        return cls(indptr, indices, data, aux[0], aux[1])

    @property
    def cap(self) -> int:
        return int(self.indices.shape[0])

    @property
    def valid(self) -> jax.Array:
        return jnp.arange(self.cap) < self.nnz

    def row_ids(self) -> jax.Array:
        """Expand indptr → per-slot row ids [cap] (padding slots → n_rows).
        The reference calls this csr_to_coo / expand (sparse/convert/coo.cuh)."""
        # row of slot i = (# row starts ≤ i) − 1, via searchsorted
        slots = jnp.arange(self.cap)
        rows = jnp.searchsorted(self.indptr, slots, side="right") - 1
        return jnp.where(self.valid, rows.astype(jnp.int32), self.shape[0])

    @classmethod
    def from_dense(cls, m, *, tol: float = 0.0) -> "CSR":
        m = np.asarray(m)
        mask = np.abs(m) > tol
        indptr = np.concatenate([[0], np.cumsum(mask.sum(1))]).astype(np.int32)
        r, c = np.nonzero(mask)
        return cls(indptr, c.astype(np.int32), m[r, c], m.shape)

    def to_dense(self) -> jax.Array:
        out = jnp.zeros(self.shape, self.data.dtype)
        r = self.row_ids()
        v = self.valid
        return out.at[r, self.indices].add(jnp.where(v, self.data, 0), mode="drop")
