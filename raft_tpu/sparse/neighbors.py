"""Sparse neighbors: brute-force kNN over CSR data + kNN-graph builder
(ref: sparse/neighbors/{brute_force,knn,knn_graph}.cuh;
cross_component_nn lives with the MST solver in raft_tpu.sparse.solver).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources, ensure
from raft_tpu.ops.matrix import merge_topk, select_k
from raft_tpu.sparse.distance import _densify_rows
from raft_tpu.sparse.formats import COO, CSR
from raft_tpu.core.trace import traced


@traced("neighbors.brute_force_knn")
def brute_force_knn(
    dataset: CSR,
    queries: CSR,
    k: int,
    *,
    metric: str = "sqeuclidean",
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN between sparse row sets — block-tiled distances + running
    top-k merge (ref: sparse/neighbors/brute_force.cuh block-tiled design)."""
    res = ensure(res)
    n, d = dataset.shape
    q = queries.shape[0]
    if k > n:
        raise ValueError(f"k={k} > dataset rows {n}")
    from raft_tpu.distance.pairwise import pairwise_distance

    tile = max(k, min(n, res.workspace_rows(4 * (2 * d + q), cap=4096)))
    # densify query tiles once, reused against every dataset block
    q_tiles = [
        _densify_rows(queries, s, min(tile, q - s)) for s in range(0, q, tile)
    ]
    vals = idx = None
    for s in range(0, n, tile):
        cnt = min(tile, n - s)
        blk = _densify_rows(dataset, s, cnt)
        dist = jnp.concatenate(
            [pairwise_distance(qb, blk, metric=metric, res=res) for qb in q_tiles],
            axis=0,
        )
        kk = min(k, cnt)
        v, i = select_k(dist, kk, select_min=True)
        i = i + s
        if kk < k:  # pad short first block so merge shapes line up
            pad = k - kk
            v = jnp.concatenate([v, jnp.full((q, pad), jnp.inf, v.dtype)], axis=1)
            i = jnp.concatenate([i, jnp.full((q, pad), -1, i.dtype)], axis=1)
        if vals is None:
            vals, idx = v, i
        else:
            vals, idx = merge_topk(vals, idx, v, i, k)
    return vals, idx


@traced("neighbors.knn_graph")
def knn_graph(
    dataset,
    k: int,
    *,
    metric: str = "sqeuclidean",
    res: Optional[Resources] = None,
) -> COO:
    """Symmetric kNN adjacency graph of a dense dataset as COO — the input to
    MST/single-linkage pipelines (ref: sparse/neighbors/knn_graph.cuh)."""
    from raft_tpu.neighbors import brute_force as dense_bf
    from raft_tpu.sparse.linalg import symmetrize

    res = ensure(res)
    x = jnp.asarray(dataset, jnp.float32)
    n = x.shape[0]
    dists, ids = dense_bf.knn(x, x, k + 1, metric=metric, res=res)
    # drop self column wherever it landed
    self_col = ids == jnp.arange(n, dtype=ids.dtype)[:, None]
    order = jnp.argsort(self_col, axis=1, stable=True)
    ids = jnp.take_along_axis(ids, order, axis=1)[:, :k]
    dists = jnp.take_along_axis(dists, order, axis=1)[:, :k]
    rows = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k)
    coo = COO(rows, ids.reshape(-1), dists.reshape(-1), (n, n))
    return symmetrize(coo, op="max")
