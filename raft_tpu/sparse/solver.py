"""Graph solvers: Borůvka MST, connected components, cross-component 1-NN
(ref: sparse/mst/mst_solver.cuh MST<...>::solve;
sparse/neighbors/cross_component_nn.cuh — both are the backbone of
single-linkage clustering, SURVEY §2.6).

TPU re-design: the reference's MST is Borůvka with per-vertex atomics and a
union-find on device. Borůvka is naturally segment-parallel: each round is
(1) segment-min over edges to find every component's lightest outgoing edge,
(2) symmetry-broken pointer hookup, (3) pointer-jumping until labels settle —
all static-shape `segment_min`/gather programs inside one ``lax.while_loop``
(≤ ⌈log₂ n⌉ rounds). No atomics, no data-dependent shapes.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.resources import Resources, ensure
from raft_tpu.sparse.formats import COO
from raft_tpu.core.trace import traced

_INT_MAX = jnp.iinfo(jnp.int32).max


def _pointer_jump(parent: jax.Array) -> jax.Array:
    """Collapse a parent forest to root labels (log-depth jumping)."""

    def cond(p):
        return jnp.any(p[p] != p)

    def body(p):
        return p[p]

    return lax.while_loop(cond, body, parent)


@functools.partial(jax.jit, static_argnames=("n",))
def _mst_jit(rows, cols, weights, valid, n: int):
    m = rows.shape[0]
    edge_ids = jnp.arange(m, dtype=jnp.int32)

    def cond(state):
        comp, chosen, any_cross = state
        return any_cross

    def body(state):
        comp, chosen, _ = state
        cs = comp[jnp.clip(rows, 0, n - 1)]
        cd = comp[jnp.clip(cols, 0, n - 1)]
        cross = valid & (cs != cd)
        # lightest outgoing edge per source component. Ties MUST break on a
        # globally consistent *undirected* key — (weight, lo, hi) — or the
        # hookup digraph can form cycles longer than 2 (equal-weight triangle
        # → 3-cycle → pointer jumping never terminates). With a total order
        # on undirected edges every hookup cycle degenerates to the mutual
        # pair handled below.
        seg = jnp.where(cross, cs, n)
        csafe = jnp.clip(cs, 0, n - 1)
        w = jnp.where(cross, weights, jnp.inf)
        wmin = jax.ops.segment_min(w, seg, num_segments=n + 1)[:n]      # [n]
        tie = cross & (weights == wmin[csafe])
        lo = jnp.minimum(rows, cols)
        hi = jnp.maximum(rows, cols)
        lmin = jax.ops.segment_min(
            jnp.where(tie, lo, _INT_MAX), seg, num_segments=n + 1
        )[:n]
        tie = tie & (lo == lmin[csafe])
        hmin = jax.ops.segment_min(
            jnp.where(tie, hi, _INT_MAX), seg, num_segments=n + 1
        )[:n]
        tie = tie & (hi == hmin[csafe])
        emin = jax.ops.segment_min(
            jnp.where(tie, edge_ids, _INT_MAX), seg, num_segments=n + 1
        )[:n]                                                            # [n]
        has = jnp.isfinite(wmin) & (emin < _INT_MAX)
        # hookup: component a points to comp[dst of its min edge]
        safe_e = jnp.clip(emin, 0, m - 1)
        target = jnp.where(has, cd[safe_e], jnp.arange(n, dtype=jnp.int32))
        # symmetry break for mutual pairs (a↔b): larger label yields
        a = jnp.arange(n, dtype=jnp.int32)
        mutual = target[jnp.clip(target, 0, n - 1)] == a
        parent = jnp.where(mutual & (a < target), a, target)
        parent = _pointer_jump(parent)
        # record chosen edges (one per hooking component; mutual pair keeps
        # both picks but they are the same undirected edge only if ids match;
        # dedupe below keeps the mask exact for the kept edge ids)
        hooked = has & ~(mutual & (a < target))
        chosen = chosen.at[jnp.where(hooked, emin, m)].set(True, mode="drop")
        new_comp = parent[comp]
        cs2 = new_comp[jnp.clip(rows, 0, n - 1)]
        cd2 = new_comp[jnp.clip(cols, 0, n - 1)]
        return new_comp, chosen, jnp.any(valid & (cs2 != cd2))

    comp0 = jnp.arange(n, dtype=jnp.int32)
    chosen0 = jnp.zeros(m, bool)
    cs = comp0[jnp.clip(rows, 0, n - 1)]
    cd = comp0[jnp.clip(cols, 0, n - 1)]
    comp, chosen, _ = lax.while_loop(
        cond, body, (comp0, chosen0, jnp.any(valid & (cs != cd)))
    )
    return comp, chosen


@traced("solver.mst")
def mst(
    graph: COO, *, res: Optional[Resources] = None
) -> Tuple[COO, jax.Array, jax.Array]:
    """Minimum spanning forest of an undirected weighted graph.

    Returns (mst_edges COO, component_labels [n], total_weight). When the
    input graph is disconnected the result is a spanning forest and
    ``component_labels`` identifies the trees (ref: mst_solver.cuh solve;
    color array = labels)."""
    n = graph.shape[0]
    comp, chosen = _mst_jit(graph.rows, graph.cols, graph.data, graph.valid, n)
    chosen_np = np.asarray(chosen)
    idx = np.nonzero(chosen_np)[0]
    rows = np.asarray(graph.rows)[idx]
    cols = np.asarray(graph.cols)[idx]
    data = np.asarray(graph.data)[idx]
    # dedupe undirected duplicates (a→b and b→a picked by different rounds)
    lo = np.minimum(rows, cols)
    hi = np.maximum(rows, cols)
    _, uniq = np.unique(np.stack([lo, hi]), axis=1, return_index=True)
    uniq = np.sort(uniq)
    out = COO(rows[uniq], cols[uniq], data[uniq], graph.shape)
    total = jnp.asarray(data[uniq].sum() if uniq.size else 0.0, graph.data.dtype)
    return out, comp, total


@functools.partial(jax.jit, static_argnames=("n",))
def _cc_jit(rows, cols, valid, n: int):
    def cond(state):
        comp, changed = state
        return changed

    def body(state):
        comp, _ = state
        cs = comp[jnp.clip(rows, 0, n - 1)]
        cd = comp[jnp.clip(cols, 0, n - 1)]
        # each endpoint adopts the min label seen over its edges
        upd = jax.ops.segment_min(
            jnp.where(valid, cd, _INT_MAX),
            jnp.where(valid, rows, n),
            num_segments=n + 1,
        )[:n]
        new = jnp.minimum(comp, jnp.where(upd == _INT_MAX, comp, upd))
        new = _pointer_jump(jnp.minimum(new, new[new]))
        return new, jnp.any(new != comp)

    comp0 = jnp.arange(n, dtype=jnp.int32)
    comp, _ = lax.while_loop(cond, body, (comp0, jnp.asarray(True)))
    return comp


@traced("solver.connected_components")
def connected_components(graph: COO) -> jax.Array:
    """Component labels (min vertex id per component) by label propagation +
    pointer jumping (the reference reaches this via its MST coloring;
    weakly-connected components of the symmetrized graph)."""
    n = graph.shape[0]
    # propagate both directions: append reversed edges
    rows = jnp.concatenate([graph.rows, graph.cols])
    cols = jnp.concatenate([graph.cols, graph.rows])
    valid = jnp.concatenate([graph.valid, graph.valid])
    return _cc_jit(rows, cols, valid, n)


@jax.jit
def _cross_nn_jit(x, labels):
    """For every point: nearest point with a different label
    (masked fused 1-NN — ref: cross_component_nn.cuh's masked NN kernel,
    distance/masked_nn.cuh)."""
    from raft_tpu.distance.pairwise import _PREC

    n = x.shape[0]
    x2 = jnp.sum(x * x, axis=1)
    d2 = x2[:, None] + x2[None, :] - 2.0 * jnp.matmul(x, x.T, precision=_PREC)
    same = labels[:, None] == labels[None, :]
    d2 = jnp.where(same, jnp.inf, jnp.maximum(d2, 0.0))
    j = jnp.argmin(d2, axis=1).astype(jnp.int32)
    return j, jnp.take_along_axis(d2, j[:, None], axis=1)[:, 0]


@traced("solver.cross_component_nn")
def cross_component_nn(
    x: jax.Array,
    labels: jax.Array,
    *,
    res: Optional[Resources] = None,
) -> COO:
    """Connect components: for each component, the lightest edge to a point
    of another component (ref: sparse/neighbors/cross_component_nn.cuh
    connect_components). Returns a COO of connecting edges (one per
    component, deduped undirected)."""
    res = ensure(res)
    x = jnp.asarray(x, jnp.float32)
    labels = jnp.asarray(labels, jnp.int32)
    n = x.shape[0]
    # tile over rows to bound the [tile, n] distance matrix
    tile = max(1, min(n, res.workspace_rows(4 * n, cap=8192)))
    if tile >= n:
        j, d = _cross_nn_jit(x, labels)
    else:
        js, ds = [], []
        from raft_tpu.distance.pairwise import _PREC

        x2 = jnp.sum(x * x, axis=1)
        for s in range(0, n, tile):
            xt = x[s : s + tile]
            d2 = (
                jnp.sum(xt * xt, axis=1)[:, None]
                + x2[None, :]
                - 2.0 * jnp.matmul(xt, x.T, precision=_PREC)
            )
            same = labels[s : s + tile, None] == labels[None, :]
            d2 = jnp.where(same, jnp.inf, jnp.maximum(d2, 0.0))
            jt = jnp.argmin(d2, axis=1).astype(jnp.int32)
            js.append(jt)
            ds.append(jnp.take_along_axis(d2, jt[:, None], axis=1)[:, 0])
        j = jnp.concatenate(js)
        d = jnp.concatenate(ds)
    # lightest outgoing edge per component (host compact — tiny result)
    j_np, d_np, lab_np = np.asarray(j), np.asarray(d), np.asarray(labels)
    comps = np.unique(lab_np)
    rows, cols, vals = [], [], []
    for c in comps:
        members = np.nonzero(lab_np == c)[0]
        finite = members[np.isfinite(d_np[members])]
        if finite.size == 0:
            continue
        b = finite[np.argmin(d_np[finite])]
        rows.append(b)
        cols.append(j_np[b])
        vals.append(d_np[b])
    if not rows:
        return COO(np.zeros(0, np.int32), np.zeros(0, np.int32),
                   np.zeros(0, np.float32), (n, n))
    rows = np.asarray(rows, np.int32)
    cols = np.asarray(cols, np.int32)
    vals = np.asarray(vals, np.float32)
    lo, hi = np.minimum(rows, cols), np.maximum(rows, cols)
    _, uniq = np.unique(np.stack([lo, hi]), axis=1, return_index=True)
    uniq = np.sort(uniq)
    return COO(rows[uniq], cols[uniq], vals[uniq], (n, n))
