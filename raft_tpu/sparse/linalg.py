"""Sparse linear algebra (ref: sparse/linalg/ — spmm, sddmm, masked_matmul,
transpose, symmetrize, degree, norm).

TPU re-design: cuSPARSE calls become gather + ``segment_sum`` programs — the
XLA-native formulation of edge-parallel sparse work (SURVEY §2.6 TPU note).
Value-level functions (spmm, sddmm, masked_matmul, norms, degree) are
static-shape over the container's slot capacity and trace under jit;
structure-mutating ops (transpose keeps capacity and traces; symmetrize
changes nnz and therefore host-syncs for the new count, like the
reference's stream-sync before sizing outputs).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.sparse.formats import COO, CSR, coo_order


def spmm(csr: CSR, b: jax.Array) -> jax.Array:
    """CSR × dense → dense (ref: sparse/linalg/spmm.cuh over cuSPARSE).

    Edge-parallel: out[row[e]] += data[e] * b[col[e]] via one gather and one
    segment_sum — both VPU/HBM friendly and fusible by XLA."""
    rows = csr.row_ids()                      # padding → n_rows, dropped below
    contrib = jnp.where(csr.valid[:, None], csr.data[:, None] * b[csr.indices], 0)
    return jax.ops.segment_sum(contrib, rows, num_segments=csr.shape[0] + 1)[:-1]


def spmv(csr: CSR, x: jax.Array) -> jax.Array:
    return spmm(csr, x[:, None])[:, 0]


def sddmm(csr: CSR, a: jax.Array, b: jax.Array, *, alpha=1.0, beta=0.0) -> CSR:
    """Sampled dense-dense matmul: out_data[e] = α·(A[row[e]]·B[col[e]]) + β·C
    (ref: sparse/linalg/sddmm.cuh). b is [n_cols, d] (row-major second factor)."""
    rows = jnp.clip(csr.row_ids(), 0, csr.shape[0] - 1)
    av = a[rows]                              # [cap, d]
    bv = b[csr.indices]                       # [cap, d]
    vals = alpha * jnp.sum(av * bv, axis=1) + beta * csr.data
    vals = jnp.where(csr.valid, vals, 0)
    return CSR(csr.indptr, csr.indices, vals, csr.shape, csr.nnz)


def masked_matmul(mask: COO, a: jax.Array, b: jax.Array) -> COO:
    """A·Bᵀ evaluated only at mask positions (ref: sparse/linalg/masked_matmul.cuh)."""
    r = jnp.clip(mask.rows, 0, a.shape[0] - 1)
    c = jnp.clip(mask.cols, 0, b.shape[0] - 1)
    vals = jnp.where(mask.valid, jnp.sum(a[r] * b[c], axis=1), 0)
    return COO(mask.rows, mask.cols, vals, mask.shape, mask.nnz)


def transpose(csr: CSR) -> CSR:
    """CSRᵀ via stable sort by column (ref: sparse/linalg/transpose.cuh
    over cusparse csr2csc)."""
    coo_rows = csr.row_ids()
    n_rows, n_cols = csr.shape
    order = coo_order(csr.indices, jnp.where(csr.valid, coo_rows, 0),
                      csr.valid, n_cols)
    new_cols = jnp.where(csr.valid[order], coo_rows[order], 0)
    counts = jnp.zeros(n_cols, jnp.int32).at[
        jnp.where(csr.valid, csr.indices, n_cols)
    ].add(jnp.where(csr.valid, 1, 0), mode="drop")
    indptr = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts).astype(jnp.int32)]
    )
    data = jnp.where(csr.valid[order], csr.data[order], 0)
    return CSR(indptr, new_cols, data, (n_cols, n_rows), csr.nnz)


def symmetrize(coo: COO, *, op: str = "max") -> COO:
    """Make A symmetric: combine A and Aᵀ entries with max/min/add/mean
    (ref: sparse/linalg/symmetrize.cuh — used by kNN-graph pipelines).

    Doubles the slot capacity (A ∪ Aᵀ) and reduces coincident (i, j) pairs
    with the shared dedup in sparse.op (host-synced for the result nnz, like
    every structure-mutating container op)."""
    from raft_tpu.sparse.op import _reduce_duplicates

    assert coo.shape[0] == coo.shape[1], "symmetrize needs a square matrix"
    both = COO(
        jnp.concatenate([coo.rows, coo.cols]),
        jnp.concatenate([coo.cols, coo.rows]),
        jnp.concatenate([coo.data, coo.data]),
        coo.shape,
        # interleave validity by placing pads at the end of each half; the
        # COO valid mask is prefix-based, so rebuild with an explicit sort
        2 * coo.cap,
    )
    # the concatenated halves each carry their own padding tail; compact the
    # live slots to a prefix so the COO ``valid`` prefix-mask is correct
    live = jnp.concatenate([coo.valid, coo.valid])
    order = jnp.argsort(~live, stable=True)
    both = COO(
        both.rows[order], both.cols[order], both.data[order],
        coo.shape, 2 * coo.nnz,
    )
    return _reduce_duplicates(both, op)


def laplacian(adj: COO, *, normalized: bool = False) -> COO:
    """Graph Laplacian L = D − A (or normalized I − D^-½AD^-½) as COO
    (ref: spectral pipelines build this before the Lanczos solve,
    spectral/matrix_wrappers.hpp laplacian_matrix_t)."""
    n = adj.shape[0]
    assert adj.shape[0] == adj.shape[1]
    deg_w = weighted_degree(adj)
    diag_r = jnp.arange(n, dtype=jnp.int32)
    if normalized:
        inv_sqrt = jnp.where(deg_w > 0, 1.0 / jnp.sqrt(jnp.maximum(deg_w, 1e-30)), 0.0)
        off = -adj.data * inv_sqrt[jnp.clip(adj.rows, 0, n - 1)] * inv_sqrt[
            jnp.clip(adj.cols, 0, n - 1)
        ]
        diag_v = jnp.where(deg_w > 0, 1.0, 0.0)
    else:
        off = -adj.data
        diag_v = deg_w
    rows = jnp.concatenate([adj.rows, diag_r])
    cols = jnp.concatenate([adj.cols, diag_r])
    data = jnp.concatenate([jnp.where(adj.valid, off, 0), diag_v])
    live = jnp.concatenate([adj.valid, jnp.ones(n, bool)])
    order = jnp.argsort(~live, stable=True)
    return COO(rows[order], cols[order], data[order], adj.shape, adj.nnz + n)


def spmv_coo(coo: COO, x: jax.Array) -> jax.Array:
    """COO matrix-vector product (edge-parallel segment_sum)."""
    n, m = coo.shape
    contrib = jnp.where(coo.valid, coo.data * x[jnp.clip(coo.cols, 0, m - 1)], 0)
    return jax.ops.segment_sum(
        contrib, jnp.where(coo.valid, coo.rows, n), num_segments=n + 1
    )[:n]


def degree(coo: COO) -> jax.Array:
    """Per-row nonzero count (ref: sparse/linalg/degree.cuh)."""
    n = coo.shape[0]
    return jnp.zeros(n, jnp.int32).at[
        jnp.where(coo.valid, coo.rows, n)
    ].add(jnp.where(coo.valid, 1, 0), mode="drop")


def weighted_degree(coo: COO) -> jax.Array:
    """Per-row sum of edge weights (the d vector of spectral methods)."""
    n = coo.shape[0]
    return jnp.zeros(n, coo.data.dtype).at[
        jnp.where(coo.valid, coo.rows, n)
    ].add(jnp.where(coo.valid, coo.data, 0), mode="drop")


def row_norm_csr(csr: CSR, *, norm_type: str = "l2") -> jax.Array:
    """Per-row norms of a CSR matrix (ref: sparse/linalg/norm.cuh)."""
    rows = csr.row_ids()
    if norm_type == "l1":
        v = jnp.abs(csr.data)
    elif norm_type == "l2":
        v = csr.data * csr.data
    elif norm_type == "linf":
        v = jnp.abs(csr.data)
        m = jax.ops.segment_max(
            jnp.where(csr.valid, v, -jnp.inf), rows, num_segments=csr.shape[0] + 1
        )[:-1]
        return jnp.maximum(m, 0.0)
    else:
        raise ValueError(f"unknown norm {norm_type}")
    return jax.ops.segment_sum(
        jnp.where(csr.valid, v, 0), rows, num_segments=csr.shape[0] + 1
    )[:-1]
