"""Sparse formats, linear algebra, ops, distances, and graph primitives
(ref: cpp/include/raft/sparse/)."""

from raft_tpu.sparse.formats import COO, CSR
from raft_tpu.sparse import convert, distance, linalg, neighbors, op, solver

__all__ = ["COO", "CSR", "convert", "distance", "linalg", "neighbors", "op", "solver"]
