"""Sparse pairwise distances (ref: sparse/distance/distance.cuh:75-126
dispatch; detail/{l2,ip,lp,bin}_distance.cuh, coo_spmv strategies).

TPU re-design. The reference's COO-SpMV expansion strategies exist because
GPU shared memory can hold one sparse row per block; neither warp shuffles
nor per-row dynamic loops exist on TPU. The design here has two lanes:

* **Expanded / Gram-term metrics** (L2, IP, cosine, correlation, hellinger,
  jaccard, dice, russellrao): everything reduces to the sparse Gram matrix
  ``A·Bᵀ`` plus per-row statistics. Row statistics (norms, sums, nnz) come
  straight off the COO slots via ``segment_sum`` — no densification. The
  Gram matrix is accumulated over **feature tiles**: each tile densifies
  only ``[n_rows, tile_d]`` columns of each operand and feeds one MXU
  matmul, so peak memory is ``O(n·tile_d)`` and *independent of the total
  feature dimension* — a 50k×10M-column matrix streams through the same
  buffer as a 50k×1k one. (This replaces round-1's whole-row densify, whose
  O(tile·d) blowup made high-dim sparse infeasible — VERDICT r1 item 7.)
* **Elementwise metrics** (L1, Linf, Canberra, Lp, Bray-Curtis,
  Jensen-Shannon, Hamming, KL): per-dimension terms are additive (max-
  additive for Linf), so the same feature tiling applies with a
  [row_tile, n_b, tile_d] broadcast kernel and per-metric partial
  accumulators (numerator/denominator pairs where the metric is a ratio).

Both lanes match the dense ``pairwise_distance`` formulas exactly on
materialized inputs (tested vs scipy).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.resources import Resources, ensure
from raft_tpu.core.trace import traced
from raft_tpu.distance.pairwise import DISTANCE_TYPES, _PREC
from raft_tpu.sparse.formats import CSR

_GRAM_METRICS = {
    "sqeuclidean",
    "euclidean",
    "inner_product",
    "cosine",
    "correlation",
    "hellinger",
    "jaccard",
    "dice",
    "russellrao",
}

_ELEMENTWISE_METRICS = {
    "l1",
    "chebyshev",
    "canberra",
    "minkowski",
    "braycurtis",
    "jensenshannon",
    "hamming",
    "kl_divergence",
}


def _densify_rows(csr: CSR, start: int, count: int) -> jax.Array:
    """Rows [start, start+count) as a dense [count, n_cols] block — the
    row-block tiling unit used by sparse brute-force kNN
    (ref: sparse/neighbors/brute_force.cuh)."""
    rows = csr.row_ids()
    n_cols = csr.shape[1]
    local = rows - start
    in_tile = csr.valid & (local >= 0) & (local < count)
    r = jnp.where(in_tile, local, count)
    out = jnp.zeros((count + 1, n_cols), csr.data.dtype)
    out = out.at[r, csr.indices].add(jnp.where(in_tile, csr.data, 0), mode="drop")
    return out[:count]


# ---------------------------------------------------------------------------
# row statistics — pure segment ops over COO slots, no densify
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("n_rows",))
def _row_stats(indptr, indices, data, valid, n_rows: int):
    """(norm2, sum, nnz) per row via segment_sum over the slot axis
    (ref: sparse/linalg/norm.cuh row norms)."""
    slots = jnp.arange(indices.shape[0])
    rows = jnp.searchsorted(indptr, slots, side="right") - 1
    rows = jnp.where(valid, rows, n_rows)  # padding → dropped segment
    w = jnp.where(valid, data.astype(jnp.float32), 0.0)
    norm2 = jax.ops.segment_sum(w * w, rows, num_segments=n_rows + 1)[:n_rows]
    s = jax.ops.segment_sum(w, rows, num_segments=n_rows + 1)[:n_rows]
    nnz = jax.ops.segment_sum(
        (w != 0).astype(jnp.float32), rows, num_segments=n_rows + 1
    )[:n_rows]
    return norm2, s, nnz


def row_norms_sq(csr: CSR) -> jax.Array:
    """‖row‖² for every row (segment-op; no densify)."""
    n2, _, _ = _row_stats(csr.indptr, csr.indices, csr.data, csr.valid, csr.shape[0])
    return n2


# ---------------------------------------------------------------------------
# feature-tiled densify + Gram accumulation
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("tile_d", "transform"))
def _densify_dtile(csr: CSR, col_start, tile_d: int, transform: str = "none"):
    """Columns [col_start, col_start+tile_d) of all rows as a dense block.

    One scatter-add over the slot axis; ``transform`` applies before the
    scatter (sqrt for hellinger)."""
    rows = csr.row_ids()  # padding slots → n_rows (dropped)
    local_c = csr.indices - col_start
    in_tile = csr.valid & (local_c >= 0) & (local_c < tile_d)
    r = jnp.where(in_tile, rows, csr.shape[0])
    v = csr.data.astype(jnp.float32)
    if transform == "sqrt":
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    out = jnp.zeros((csr.shape[0] + 1, tile_d), jnp.float32)
    out = out.at[r, jnp.clip(local_c, 0, tile_d - 1)].add(
        jnp.where(in_tile, v, 0.0), mode="drop"
    )
    return out[:-1]


@functools.partial(jax.jit, static_argnames=("tile_d", "transform"))
def _gram_step(gram, a: CSR, b: CSR, col_start, tile_d: int, transform: str):
    da = _densify_dtile(a, col_start, tile_d, transform)
    db = _densify_dtile(b, col_start, tile_d, transform)
    return gram + jnp.matmul(da, db.T, precision=_PREC)


def _sparse_gram(
    a: CSR, b: CSR, res: Resources, transform: str = "none"
) -> jax.Array:
    """A·Bᵀ accumulated over feature tiles: peak memory O((n_a+n_b)·tile_d)
    regardless of the total column count (the TPU answer to the reference's
    COO-SpMV strategies, sparse/distance/detail/coo_spmv*.cuh)."""
    n_a, d = a.shape
    n_b = b.shape[0]
    per_col = 4 * (n_a + n_b)
    tile_d = int(min(d, max(128, res.workspace_limit_bytes // (2 * max(per_col, 1)))))
    gram = jnp.zeros((n_a, n_b), jnp.float32)
    for s in range(0, d, tile_d):
        gram = _gram_step(gram, a, b, jnp.int32(s), tile_d, transform)
    return gram


# ---------------------------------------------------------------------------
# elementwise lane: feature-tiled partial accumulators
# ---------------------------------------------------------------------------


def _ew_partial(da, db, metric: str, p: float):
    """Per-(row-pair) partial terms over one feature tile.
    da: [ta, td], db: [nb, td] → tuple of [ta, nb] partials."""
    x = da[:, None, :]
    y = db[None, :, :]
    if metric == "l1":
        return (jnp.sum(jnp.abs(x - y), -1),)
    if metric == "chebyshev":
        return (jnp.max(jnp.abs(x - y), -1),)
    if metric == "canberra":
        num = jnp.abs(x - y)
        den = jnp.abs(x) + jnp.abs(y)
        return (jnp.sum(jnp.where(den > 0, num / jnp.maximum(den, 1e-30), 0.0), -1),)
    if metric == "minkowski":
        return (jnp.sum(jnp.abs(x - y) ** p, -1),)
    if metric == "braycurtis":
        return (jnp.sum(jnp.abs(x - y), -1), jnp.sum(jnp.abs(x + y), -1))
    if metric == "jensenshannon":
        m = 0.5 * (x + y)
        safe_log = lambda a_, b_: jnp.where(
            a_ > 0, a_ * jnp.log(jnp.maximum(a_, 1e-30) / jnp.maximum(b_, 1e-30)), 0.0
        )
        return (jnp.sum(safe_log(x, m) + safe_log(y, m), -1),)
    if metric == "hamming":
        return (jnp.sum((x != y).astype(jnp.float32), -1),)
    if metric == "kl_divergence":
        return (
            jnp.sum(
                jnp.where(
                    x > 0,
                    x * jnp.log(jnp.maximum(x, 1e-30) / jnp.maximum(y, 1e-30)),
                    0.0,
                ),
                -1,
            ),
        )
    raise ValueError(metric)


def _ew_finalize(partials, metric: str, p: float, d: int):
    if metric == "minkowski":
        return partials[0] ** (1.0 / p)
    if metric == "braycurtis":
        num, den = partials
        return jnp.where(den > 0, num / jnp.maximum(den, 1e-30), 0.0)
    if metric == "jensenshannon":
        return jnp.sqrt(jnp.maximum(0.5 * partials[0], 0.0))
    if metric == "hamming":
        return partials[0] / d
    return partials[0]


@functools.partial(jax.jit, static_argnames=("metric", "p", "tile_d", "tile_a"))
def _ew_dtile(
    partials, a: CSR, b: CSR, col_start, metric: str, p: float,
    tile_d: int, tile_a: int,
):
    n_a, n_b = a.shape[0], b.shape[0]
    combine = jnp.maximum if metric == "chebyshev" else jnp.add
    da = _densify_dtile(a, col_start, tile_d)
    db = _densify_dtile(b, col_start, tile_d)
    n_ta = (n_a + tile_a - 1) // tile_a
    pad = n_ta * tile_a - n_a
    dap = jnp.pad(da, ((0, pad), (0, 0))).reshape(n_ta, tile_a, tile_d)
    parts = lax.map(lambda t: _ew_partial(t, db, metric, p), dap)
    parts = tuple(pp.reshape(n_ta * tile_a, n_b)[:n_a] for pp in parts)
    return tuple(combine(acc, pp) for acc, pp in zip(partials, parts))


def _elementwise_sparse(a: CSR, b: CSR, metric: str, p: float, res: Resources):
    n_a, d = a.shape
    n_b = b.shape[0]
    # feature tile bounded by the [ta, n_b, td] broadcast
    tile_d = int(min(d, max(64, res.workspace_rows(4 * (n_a + n_b), cap=4096))))
    tile_a = max(8, res.workspace_rows(4 * n_b * tile_d, cap=4096))
    n_acc = 2 if metric == "braycurtis" else 1
    partials = tuple(jnp.zeros((n_a, n_b), jnp.float32) for _ in range(n_acc))
    for s in range(0, d, tile_d):
        partials = _ew_dtile(
            partials, a, b, jnp.int32(s), metric, float(p), tile_d, tile_a
        )
    return _ew_finalize(partials, metric, p, d)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


@traced("distance.pairwise_distance_sparse")
def pairwise_distance_sparse(
    a: CSR,
    b: CSR,
    *,
    metric: str = "sqeuclidean",
    p: float = 2.0,
    res: Optional[Resources] = None,
) -> jax.Array:
    """All-pairs distance between CSR row sets → dense [a_rows, b_rows]
    (ref: sparse/distance/distance.cuh pairwise_distance; metric coverage
    mirrors the reference's 15-metric sparse dispatch :75-126)."""
    res = ensure(res)
    if a.shape[1] != b.shape[1]:
        raise ValueError(f"column mismatch {a.shape} vs {b.shape}")
    canonical = DISTANCE_TYPES[metric]
    d = a.shape[1]

    if canonical in _ELEMENTWISE_METRICS:
        return _elementwise_sparse(a, b, canonical, p, res)
    if canonical not in _GRAM_METRICS:
        raise ValueError(f"unsupported sparse metric {metric!r}")

    if canonical == "hellinger":
        ip = _sparse_gram(a, b, res, transform="sqrt")
        return jnp.sqrt(jnp.maximum(1.0 - ip, 0.0))

    ip = _sparse_gram(a, b, res)
    n2a, sa, _ = _row_stats(a.indptr, a.indices, a.data, a.valid, a.shape[0])
    n2b, sb, _ = _row_stats(b.indptr, b.indices, b.data, b.valid, b.shape[0])

    if canonical == "inner_product":
        return ip
    if canonical in ("euclidean", "sqeuclidean"):
        d2 = jnp.maximum(n2a[:, None] + n2b[None, :] - 2.0 * ip, 0.0)
        return jnp.sqrt(d2) if canonical == "euclidean" else d2
    if canonical == "cosine":
        denom = jnp.sqrt(n2a)[:, None] * jnp.sqrt(n2b)[None, :]
        return 1.0 - ip / jnp.maximum(denom, 1e-30)
    if canonical == "correlation":
        cip = ip - sa[:, None] * sb[None, :] / d
        vx = jnp.maximum(n2a - sa * sa / d, 0.0)
        vy = jnp.maximum(n2b - sb * sb / d, 0.0)
        denom = jnp.sqrt(vx[:, None] * vy[None, :])
        return jnp.where(denom > 1e-12, 1.0 - cip / jnp.maximum(denom, 1e-12), 1.0)
    if canonical == "jaccard":
        union = sa[:, None] + sb[None, :] - ip
        return jnp.where(union > 0, 1.0 - ip / jnp.maximum(union, 1e-30), 0.0)
    if canonical == "dice":
        tot = sa[:, None] + sb[None, :]
        return jnp.where(tot > 0, 1.0 - 2.0 * ip / jnp.maximum(tot, 1e-30), 0.0)
    if canonical == "russellrao":
        return (d - ip) / d
    raise ValueError(canonical)
