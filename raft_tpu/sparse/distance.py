"""Sparse pairwise distances (ref: sparse/distance/distance.cuh:75-126
dispatch; detail/{l2,ip,lp,bin}_distance.cuh, coo_spmv strategies).

TPU re-design: the reference's COO-SpMV expansion strategies exist because
GPU shared memory can hold one sparse row per block. On TPU the MXU wants
dense tiles, so the design is **tile-densify + dense kernel reuse**: stream
row-blocks of each CSR operand into dense [tile, d] buffers and call the
dense pairwise-distance path (SURVEY §2.6 "dense-fallback (BCOO)" note).
Exact for every supported metric, memory-bounded by the tile size, and the
inner loop is the same MXU matmul the dense path uses. A future Pallas CSR
kernel can slot in behind the same API.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources, ensure
from raft_tpu.distance.pairwise import DISTANCE_TYPES, pairwise_distance
from raft_tpu.sparse.formats import CSR
from raft_tpu.core.trace import traced


def _densify_rows(csr: CSR, start: int, count: int) -> jax.Array:
    """Rows [start, start+count) as a dense [count, n_cols] block."""
    rows = csr.row_ids()
    n_cols = csr.shape[1]
    local = rows - start
    in_tile = csr.valid & (local >= 0) & (local < count)
    r = jnp.where(in_tile, local, count)
    out = jnp.zeros((count + 1, n_cols), csr.data.dtype)
    out = out.at[r, csr.indices].add(jnp.where(in_tile, csr.data, 0), mode="drop")
    return out[:count]


@traced("distance.pairwise_distance_sparse")
def pairwise_distance_sparse(
    a: CSR,
    b: CSR,
    *,
    metric: str = "sqeuclidean",
    p: float = 2.0,
    res: Optional[Resources] = None,
) -> jax.Array:
    """All-pairs distance between CSR row sets → dense [a_rows, b_rows]
    (ref: sparse/distance/distance.cuh pairwise_distance)."""
    res = ensure(res)
    if a.shape[1] != b.shape[1]:
        raise ValueError(f"column mismatch {a.shape} vs {b.shape}")
    DISTANCE_TYPES[metric]  # validate
    n_a, n_b = a.shape[0], b.shape[0]
    d = a.shape[1]
    # tile so both densified blocks + the output tile fit the workspace
    tile = max(1, min(max(n_a, n_b), res.workspace_rows(4 * (2 * d + n_b), cap=4096)))
    # densify b blocks once and reuse them against every a block when the
    # whole densified b fits the workspace; otherwise re-densify per a block
    cache_b = 4 * n_b * d <= res.workspace_limit_bytes
    b_blocks = (
        [_densify_rows(b, t, min(tile, n_b - t)) for t in range(0, n_b, tile)]
        if cache_b
        else None
    )
    out_rows = []
    for s in range(0, n_a, tile):
        cnt = min(tile, n_a - s)
        a_blk = _densify_rows(a, s, cnt)
        col_parts = []
        for bi, t in enumerate(range(0, n_b, tile)):
            b_blk = (
                b_blocks[bi]
                if b_blocks is not None
                else _densify_rows(b, t, min(tile, n_b - t))
            )
            col_parts.append(
                pairwise_distance(a_blk, b_blk, metric=metric, p=p, res=res)
            )
        out_rows.append(jnp.concatenate(col_parts, axis=1))
    return jnp.concatenate(out_rows, axis=0)
