"""Primitive-level microbenchmarks — bench/prims parity.

Reference: ``cpp/bench/prims/`` runs Google-Benchmark timings per primitive
(matrix/select_k, distance, linalg, cluster, random). Here: a table of
wall-clock timings for the hot primitives, runnable on any backend:

    python -m raft_tpu.bench.prims [--out results.json] [--filter select_k]

Timings amortize dispatch latency over inner iterations (the axon tunnel
costs ~75 ms per dispatch, so single-call timing would be meaningless —
measured in round 2).
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import time
from typing import Callable, Dict, List

import numpy as np

# platform override must land before any backend init (same contract as
# raft_tpu.bench.__main__); direct read: core.env would import raft_tpu
# and therefore jax before the platform override lands
if os.environ.get("RAFT_TPU_PLATFORM"):  # raft-tpu: ignore[ENVREG] pre-jax bootstrap
    import jax

    jax.config.update("jax_platforms", os.environ["RAFT_TPU_PLATFORM"])  # raft-tpu: ignore[ENVREG] pre-jax bootstrap

from raft_tpu.core import env as _env  # noqa: E402 — after platform override


def _timeit(fn: Callable, args, warmup: int = 2, iters: int = 5) -> float:
    import jax

    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def _cases() -> List[Dict]:
    import jax
    import jax.numpy as jnp

    from raft_tpu.distance.fused_nn import fused_l2_nn_argmin
    from raft_tpu.distance.pairwise import pairwise_distance
    from raft_tpu.ops.matrix import select_k

    rng = np.random.default_rng(0)
    cases = []

    # NB: operands are passed as call arguments, never closed over — a
    # closed-over array becomes an XLA constant and the whole benchmark gets
    # constant-folded at compile time.

    # select_k (ref: bench/prims/matrix/select_k.cu shapes); the explicit
    # algo cases A/B the wide-top_k vs chunked-tournament paths to tune the
    # auto heuristic (_CHUNKED_MIN_N — the select_k-inl.cuh:47 analog)
    for rows, cols, k in [(1024, 16384, 64), (128, 131072, 256), (4096, 2048, 10)]:
        x = jnp.asarray(rng.standard_normal((rows, cols)).astype(np.float32))
        fn = jax.jit(functools.partial(select_k, k=k, select_min=True))
        cases.append(
            {
                "name": f"select_k/{rows}x{cols}/k{k}",
                "fn": fn,
                "args": (x,),
                "bytes": rows * cols * 4,
                "flops": 0,
            }
        )
    # decision-boundary sweep for the auto heuristic: cols crosses the
    # current _CHUNKED_MIN_N=8192 from both sides at the k values the
    # dispatch branches on (fit with benchmarks/fit_heuristics.py).
    # ONE device array per (rows, cols), shared across the k/algo grid —
    # per-k copies would hold ~3x the HBM for the whole run
    ab_shapes = {(1024, c): (10, 64, 256) for c in
                 (4096, 8192, 16384, 32768, 131072)}
    ab_shapes[(64, 1_000_000)] = (100,)
    ab_shapes[(4096, 8192)] = (16,)
    for (rows, cols), ks in ab_shapes.items():
        x = jnp.asarray(rng.standard_normal((rows, cols)).astype(np.float32))
        for k in ks:
            for algo in ("topk", "chunked"):
                fn = jax.jit(
                    functools.partial(select_k, k=k, select_min=True, algo=algo)
                )
                cases.append(
                    {
                        "name": f"select_k_ab/{rows}x{cols}/k{k}/{algo}",
                        "fn": fn,
                        "args": (x,),
                        "bytes": rows * cols * 4,
                        "flops": 0,
                    }
                )

    # pairwise distance (ref: bench/prims/distance/)
    for m, n, d, metric in [(2048, 2048, 128, "sqeuclidean"), (1024, 1024, 512, "l1")]:
        a = jnp.asarray(rng.standard_normal((m, d)).astype(np.float32))
        b = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
        fn = jax.jit(functools.partial(pairwise_distance, metric=metric))
        cases.append(
            {
                "name": f"pairwise/{metric}/{m}x{n}x{d}",
                "fn": fn,
                "args": (a, b),
                "bytes": (m + n) * d * 4 + m * n * 4,
                "flops": 2 * m * n * d,
            }
        )

    # IVF-PQ scan-strategy A/B (query-major vs probe-major schedules —
    # tune ivf_pq.SearchParams.strategy's auto rule from the chip numbers;
    # the analog of the reference's compute_similarity kernel-variant
    # selection)
    from raft_tpu.neighbors import ivf_pq as _pq

    # index built lazily on the first (warmup) call so a --filter that
    # skips these cases never pays the 100k build
    _scan_state: Dict = {}

    def _scan_index():
        if "index" not in _scan_state:
            blob_c = rng.standard_normal((512, 96)).astype(np.float32) * 4
            asg = rng.integers(0, 512, 100_000)
            xb = blob_c[asg] + rng.standard_normal((100_000, 96)).astype(np.float32)
            _scan_state["index"] = _pq.build(
                _pq.IndexParams(n_lists=1024, pq_dim=48, kmeans_n_iters=5), xb
            )
        return _scan_state["index"]

    qs = jnp.asarray(rng.standard_normal((4096, 96)).astype(np.float32))
    # logical scan traffic per query-major pass: probed rows × bf16 row
    # bytes at the *mean* occupancy (n/n_lists) — padding excluded, and the
    # probe-major case reads far less physically; gbps here is a
    # schedule-comparable "effective" rate, not measured HBM bandwidth
    scan_bytes = 4096 * 32 * (100_000 // 1024) * 96 * 2
    for strat, pallas in (
        ("query_major", False), ("query_major", True),
        ("probe_major", False), ("probe_major", True),
    ):
        sp = _pq.SearchParams(n_probes=32, strategy=strat)

        def scan_fn(q, _sp=sp, _pallas=pallas):
            # the Pallas gate is read per search call, so the A/B leg can
            # flip it around the dispatch (promotion evidence: VERDICT r3
            # item 10 — default-on requires this case to win on chip)
            prev = _env.raw("RAFT_TPU_PALLAS")
            if _pallas:
                os.environ["RAFT_TPU_PALLAS"] = "1"
            else:
                os.environ.pop("RAFT_TPU_PALLAS", None)
            try:
                return _pq.search(_sp, _scan_index(), q, 10)
            finally:
                if prev is None:
                    os.environ.pop("RAFT_TPU_PALLAS", None)
                else:
                    os.environ["RAFT_TPU_PALLAS"] = prev

        cases.append(
            {
                "name": f"ivf_scan_ab/100kx96/p32/{strat}"
                + ("_pallas" if pallas else ""),
                "fn": scan_fn,
                "args": (qs,),
                "bytes": scan_bytes,
                "flops": 0,
            }
        )

    # brute-force kNN A/B: XLA tiled formulation vs the fused Pallas
    # distance+topk kernel — the promotion evidence for fused_knn
    # (mirrors ivf_scan_ab; VERDICT r3 item 10)
    from raft_tpu.neighbors import brute_force as _bf

    bx = jnp.asarray(rng.standard_normal((200_000, 96)).astype(np.float32))
    bq = jnp.asarray(rng.standard_normal((4096, 96)).astype(np.float32))

    for pallas in (False, True):
        def bf_fn(xx, qq, _pallas=pallas):
            prev = _env.raw("RAFT_TPU_PALLAS")
            if _pallas:
                os.environ["RAFT_TPU_PALLAS"] = "1"
            else:
                os.environ.pop("RAFT_TPU_PALLAS", None)
            try:
                return _bf.knn(xx, qq, 10)
            finally:
                if prev is None:
                    os.environ.pop("RAFT_TPU_PALLAS", None)
                else:
                    os.environ["RAFT_TPU_PALLAS"] = prev

        cases.append(
            {
                "name": "bf_knn_ab/200kx96/q4096/k10"
                + ("/pallas" if pallas else "/xla"),
                "fn": bf_fn,
                "args": (bx, bq),
                "bytes": 200_000 * 96 * 4,
                "flops": 2 * 200_000 * 4096 * 96,
            }
        )

    # fused L2 argmin — the kmeans inner loop (ref: bench/prims/distance/fused_l2_nn.cu)
    m, n, d = 8192, 1024, 128
    a = jnp.asarray(rng.standard_normal((m, d)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
    cases.append(
        {
            "name": f"fused_l2_nn/{m}x{n}x{d}",
            "fn": jax.jit(fused_l2_nn_argmin),
            "args": (a, b),
            "bytes": (m + n) * d * 4,
            "flops": 2 * m * n * d,
        }
    )
    return cases


def run(filter_: str = "", out_path: str = "") -> List[Dict]:
    import os

    import jax

    # per-case checkpoint (mirrors benchmarks/frontier.py): an on-chip
    # sweep killed by a tunnel death resumes from <out>.partial instead
    # of re-timing every completed case
    part = out_path + ".partial" if out_path else ""
    results: List[Dict] = []
    done = set()
    if part and os.path.exists(part):
        try:
            with open(part) as f:
                results = json.load(f)
            done = {r["name"] for r in results}
            print(f"resuming from {part}: {len(done)} cases done")
        except Exception:
            results, done = [], set()
    for case in _cases():
        if filter_ and filter_ not in case["name"]:
            continue
        if case["name"] in done:
            continue
        s = _timeit(case["fn"], case["args"])
        row = {
            "name": case["name"],
            "seconds": round(s, 6),
            "gbps": round(case["bytes"] / s / 1e9, 2),
            "gflops": round(case["flops"] / s / 1e9, 2) if case["flops"] else None,
            "platform": jax.devices()[0].platform,
        }
        results.append(row)
        print(json.dumps(row))
        if part:
            with open(part, "w") as f:
                json.dump(results, f)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(results, f, indent=2)
        if part and os.path.exists(part):
            os.remove(part)
    return results


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--filter", default="", help="substring filter on case names")
    ap.add_argument("--out", default="", help="write JSON results here")
    args = ap.parse_args()
    run(args.filter, args.out)


if __name__ == "__main__":
    main()
