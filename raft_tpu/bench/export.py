"""Result export: CSV tables, schema-versioned bench records, and the
noise-aware record comparator behind ``bench.py compare``.

CSV side (ref: raft-ann-bench data_export — flattens the per-run JSON
into build/search CSV tables for plotting) is unchanged.  The record side
is the regression gate: every bench leg wraps its one-line JSON payload
in :func:`bench_record` and writes it via :func:`write_bench_record`, so
any two runs — across rounds, machines, or branches — can be diffed with
:func:`compare_records`.  Thresholds are *noise-aware*: throughput and
latency compare relatively (default ±25%, wide enough for shared-CPU CI
jitter, narrow enough to catch a 2x regression), recall compares with an
absolute tolerance, and a hot-path recompile appearing where the
baseline had none is always a failure regardless of timing.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Dict, List, Optional, Tuple

from raft_tpu.bench.runner import RunResult
from raft_tpu.core import env as _env

_FIELDS = [
    "algo", "dataset", "k", "build_param", "search_param",
    "build_time_s", "qps", "latency_ms", "recall", "end_to_end_s",
    "device_time_s", "device_qps",
]

#: bump when the record envelope (not the payload) changes shape
BENCH_SCHEMA_VERSION = 1

#: env var naming the default record path bench legs write to
RECORD_PATH_ENV = "RAFT_TPU_BENCH_RECORD"
DEFAULT_RECORD_PATH = "BENCH_last.json"


def to_csv(results: List[RunResult], path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", newline="") as fh:
        w = csv.DictWriter(fh, fieldnames=_FIELDS)
        w.writeheader()
        for r in results:
            d = r.to_dict()
            d["build_param"] = json.dumps(d["build_param"])
            d["search_param"] = json.dumps(d["search_param"])
            w.writerow(d)


def from_json(path: str) -> List[RunResult]:
    with open(path) as fh:
        return [RunResult(**d) for d in json.load(fh)]


# ---- schema-versioned bench records ----------------------------------------

def kernel_path(
    metric: Optional[str] = None,
    storage_dtype=None,
    *,
    pallas: Optional[bool] = None,
) -> Dict[str, object]:
    """Which kernel implementation a record's numbers are attributable to.

    Every record carries this (stamped by :func:`bench_record` if the leg
    didn't set it), so "pallas won X%" claims are checkable against the
    record instead of against memory.  Pass ``pallas=`` when the leg
    measured the routing itself (the accel A/B leg does); pass
    ``metric``/``storage_dtype`` to ask the shared
    :func:`~raft_tpu.neighbors._common.pallas_scan_enabled` gate; with
    neither, fall back to the ``RAFT_TPU_PALLAS`` env opt-in alone.
    """
    if pallas is None:
        if metric is not None and storage_dtype is not None:
            from raft_tpu.neighbors._common import pallas_scan_enabled

            pallas = pallas_scan_enabled(metric, storage_dtype)
        else:
            pallas = _env.env_str("RAFT_TPU_PALLAS") == "1"
    return {"pallas": bool(pallas)}


def bench_record(payload: Dict[str, object]) -> Dict[str, object]:
    """Wrap one bench leg's JSON payload in the versioned envelope.

    Stamps a default :func:`kernel_path` into payloads that lack one —
    additive, so records written before the field existed still load and
    compare (absence is simply not reported).
    """
    if not isinstance(payload, dict) or "metric" not in payload:
        raise ValueError(
            "bench payload must be a dict with a 'metric' key, got "
            f"{type(payload).__name__}"
        )
    rec = dict(payload)
    rec.setdefault("kernel_path", kernel_path())
    return {
        "schema": "raft_tpu.bench",
        "schema_version": BENCH_SCHEMA_VERSION,
        "record": rec,
    }


def write_bench_record(
    payload: Dict[str, object], path: Optional[str] = None
) -> str:
    """Write the enveloped record; returns the path written.

    Default path comes from ``RAFT_TPU_BENCH_RECORD`` (set it to ``-`` or
    empty to suppress the write) falling back to ``BENCH_last.json`` in
    the working directory — every leg leaves a comparable artifact even
    when nobody asked for one.
    """
    if path is None:
        path = _env.env_str(RECORD_PATH_ENV, DEFAULT_RECORD_PATH)
    if not path or path == "-":
        return ""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as fh:
        json.dump(bench_record(payload), fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def load_record(path: str) -> Dict[str, object]:
    """Load a bench payload from any of the formats in the wild.

    Accepts the :func:`bench_record` envelope, the driver's historical
    ``BENCH_r0N.json`` wrapper (payload under ``"parsed"``), or a bare
    payload dict (a captured stdout line).  Returns the payload.
    """
    with open(path) as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a JSON object, got "
                         f"{type(doc).__name__}")
    if doc.get("schema") == "raft_tpu.bench":
        ver = doc.get("schema_version")
        if ver != BENCH_SCHEMA_VERSION:
            raise ValueError(
                f"{path}: unsupported bench schema_version {ver!r} "
                f"(this build reads {BENCH_SCHEMA_VERSION})"
            )
        payload = doc.get("record")
    elif "parsed" in doc:  # BENCH_r0N.json driver wrapper
        payload = doc["parsed"]
    else:
        payload = doc
    if not isinstance(payload, dict) or "metric" not in payload:
        raise ValueError(f"{path}: no bench payload with a 'metric' key")
    return payload


# ---- noise-aware comparison ------------------------------------------------

#: units where a LARGER primary value is better; everything that looks
#: like a duration (ms / s suffix) is treated as smaller-is-better
_HIGHER_IS_BETTER_UNITS = ("/s", "qps", "ops")


def _higher_is_better(unit: str) -> bool:
    u = (unit or "").lower()
    return any(tok in u for tok in _HIGHER_IS_BETTER_UNITS)


def compare_records(
    baseline: Dict[str, object],
    candidate: Dict[str, object],
    *,
    rtol: float = 0.25,
    recall_atol: float = 0.02,
) -> Tuple[bool, List[str]]:
    """Diff two bench payloads; returns (ok, report_lines).

    ``ok`` is False on any regression: primary value worse than the
    baseline by more than ``rtol`` (direction inferred from ``unit``),
    recall lower by more than ``recall_atol``, or hot-path recompiles
    appearing where the baseline had none.  Improvements and in-tolerance
    drift are reported but pass.  Records for *different* metrics (or
    different platforms) are incomparable — reported as skipped, ok=True —
    so a CI job pointed at a stale baseline degrades to a no-op instead
    of a false alarm.
    """
    lines: List[str] = []
    ok = True

    b_metric, c_metric = baseline.get("metric"), candidate.get("metric")
    if b_metric != c_metric:
        lines.append(
            f"SKIP incomparable metrics: baseline={b_metric!r} "
            f"candidate={c_metric!r}"
        )
        return True, lines
    b_plat, c_plat = baseline.get("platform"), candidate.get("platform")
    if b_plat != c_plat:
        lines.append(
            f"SKIP incomparable platforms: baseline={b_plat!r} "
            f"candidate={c_plat!r}"
        )
        return True, lines
    lines.append(f"metric {b_metric} (platform={b_plat})")

    # primary value, direction by unit
    try:
        bv = float(baseline["value"])
        cv = float(candidate["value"])
    except (KeyError, TypeError, ValueError):
        lines.append("SKIP no comparable 'value' field")
        return True, lines
    unit = str(candidate.get("unit") or baseline.get("unit") or "")
    hib = _higher_is_better(unit)
    ratio = (cv / bv) if bv else float("inf")
    worse = ratio < (1.0 - rtol) if hib else ratio > (1.0 + rtol)
    tag = "REGRESSION" if worse else "ok"
    lines.append(
        f"  value: {bv:g} -> {cv:g} {unit} "
        f"({ratio:.0%} of baseline, {'higher' if hib else 'lower'} is "
        f"better, rtol={rtol:.0%}) {tag}"
    )
    ok &= not worse

    # secondary latency percentiles (always lower-is-better)
    for field in ("p50_ms", "p99_ms", "latency_ms"):
        b, c = baseline.get(field), candidate.get(field)
        if b is None or c is None:
            continue
        b, c = float(b), float(c)
        if b <= 0:
            continue
        r = c / b
        worse = r > (1.0 + rtol)
        tag = "REGRESSION" if worse else "ok"
        lines.append(f"  {field}: {b:g} -> {c:g} ({r:.0%} of baseline) {tag}")
        ok &= not worse

    # recall: absolute tolerance — relative thresholds are meaningless on
    # a [0, 1] quantity pinned near 1
    b, c = baseline.get("recall"), candidate.get("recall")
    if b is not None and c is not None:
        b, c = float(b), float(c)
        worse = c < b - recall_atol
        tag = "REGRESSION" if worse else "ok"
        lines.append(
            f"  recall: {b:.4f} -> {c:.4f} (atol={recall_atol}) {tag}"
        )
        ok &= not worse

    # hot-path recompiles: zero tolerance once the baseline achieved zero
    b, c = baseline.get("recompiles"), candidate.get("recompiles")
    if b is not None and c is not None and int(b) == 0 and int(c) > 0:
        lines.append(
            f"  recompiles: 0 -> {int(c)} REGRESSION (hot-path XLA "
            "compiles reappeared)"
        )
        ok = False

    # kernel path: informational, never a failure — but a value delta
    # measured across a pallas-routing change is not apples-to-apples,
    # so say which kernels produced each side (absent in old records)
    b, c = baseline.get("kernel_path"), candidate.get("kernel_path")
    if (b is not None or c is not None) and b != c:
        lines.append(
            f"  kernel_path: {json.dumps(b)} -> {json.dumps(c)} "
            "(info: sides ran different kernel routings)"
        )

    lines.append("PASS" if ok else "FAIL")
    return ok, lines


def compare_main(argv: Optional[List[str]] = None) -> int:
    """CLI body shared by ``bench.py compare`` and
    ``python -m raft_tpu.bench compare``.  Exit 0 on pass/skip, 1 on
    regression, 2 on usage/IO errors."""
    import argparse

    ap = argparse.ArgumentParser(
        "bench compare",
        description="Diff two bench records with noise-aware thresholds.",
    )
    ap.add_argument("--baseline", required=True,
                    help="baseline record (BENCH_last.json / BENCH_r0N.json)")
    ap.add_argument("--candidate", default="",
                    help="candidate record; default: run the CPU bench leg "
                    "now and compare its record")
    ap.add_argument("--rtol", type=float, default=0.25,
                    help="relative tolerance for value/latency (default .25)")
    ap.add_argument("--recall-atol", type=float, default=0.02,
                    help="absolute tolerance for recall (default .02)")
    args = ap.parse_args(argv)

    try:
        baseline = load_record(args.baseline)
    except (OSError, ValueError) as e:
        print(f"compare: cannot load baseline: {e}")
        return 2
    cand_path = args.candidate
    if not cand_path:
        import subprocess
        import sys
        import tempfile

        cand_path = os.path.join(
            tempfile.mkdtemp(prefix="raft_tpu_bench_"), "candidate.json"
        )
        env = dict(os.environ, **{RECORD_PATH_ENV: cand_path})
        bench_py = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), "bench.py")
        print(f"compare: no --candidate; running {bench_py} --run-leg cpu")
        proc = subprocess.run(
            [sys.executable, bench_py, "--run-leg", "cpu"], env=env
        )
        if proc.returncode != 0 or not os.path.exists(cand_path):
            print(f"compare: candidate leg failed (rc={proc.returncode})")
            return 2
    try:
        candidate = load_record(cand_path)
    except (OSError, ValueError) as e:
        print(f"compare: cannot load candidate: {e}")
        return 2
    ok, lines = compare_records(
        baseline, candidate, rtol=args.rtol, recall_atol=args.recall_atol
    )
    print("\n".join(lines))
    return 0 if ok else 1
