"""Result export to CSV (ref: raft-ann-bench data_export — flattens the
per-run JSON into build/search CSV tables for plotting)."""

from __future__ import annotations

import csv
import json
import os
from typing import List

from raft_tpu.bench.runner import RunResult

_FIELDS = [
    "algo", "dataset", "k", "build_param", "search_param",
    "build_time_s", "qps", "latency_ms", "recall", "end_to_end_s",
    "device_time_s", "device_qps",
]


def to_csv(results: List[RunResult], path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", newline="") as fh:
        w = csv.DictWriter(fh, fieldnames=_FIELDS)
        w.writeheader()
        for r in results:
            d = r.to_dict()
            d["build_param"] = json.dumps(d["build_param"])
            d["search_param"] = json.dumps(d["search_param"])
            w.writerow(d)


def from_json(path: str) -> List[RunResult]:
    with open(path) as fh:
        return [RunResult(**d) for d in json.load(fh)]
