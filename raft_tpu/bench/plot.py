"""Recall/QPS pareto-frontier plotting (ref: raft-ann-bench plot —
throughput-vs-recall curves per algorithm, pareto-filtered)."""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Sequence, Tuple

from raft_tpu.bench.runner import RunResult


def pareto_frontier(points: Sequence[Tuple[float, float]]):
    """Keep (recall, qps) points not dominated by any other (higher recall
    AND higher qps) — the reference's frontier filter."""
    pts = sorted(points, key=lambda p: (-p[0], -p[1]))
    out, best_qps = [], -1.0
    for r, q in pts:
        if q > best_qps:
            out.append((r, q))
            best_qps = q
    return list(reversed(out))


def group_frontiers(results: List[RunResult]) -> Dict[str, list]:
    by_algo = defaultdict(list)
    for r in results:
        by_algo[r.algo].append((r.recall, r.qps))
    return {a: pareto_frontier(p) for a, p in by_algo.items()}


def plot_results(results: List[RunResult], path: str, *, title: str = "") -> None:
    """Write a recall/QPS frontier PNG (matplotlib; log-scale QPS like the
    reference's plots)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(8, 6))
    for algo, pts in sorted(group_frontiers(results).items()):
        xs = [p[0] for p in pts]
        ys = [p[1] for p in pts]
        ax.plot(xs, ys, marker="o", label=algo)
    ax.set_xlabel("Recall")
    ax.set_ylabel("QPS")
    ax.set_yscale("log")
    ax.set_title(title or (results[0].dataset if results else ""))
    ax.grid(True, alpha=0.3)
    ax.legend()
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
