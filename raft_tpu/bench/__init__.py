"""ANN benchmark harness (ref: python/raft-ann-bench/ + cpp/bench/ann/).

Components mirror the reference suite (SURVEY §2.14/§2.15):
datasets (get_dataset/generate_groundtruth), run (JSON-config orchestrator
computing QPS/latency/recall), data_export (CSV), plot (recall/QPS pareto
frontier)."""

from raft_tpu.bench import datasets, export, plot, runner

__all__ = ["datasets", "export", "plot", "runner"]
