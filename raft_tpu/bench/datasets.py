"""Benchmark datasets: binary readers/writers, synthetic generators,
groundtruth computation.

Reference: raft-ann-bench ``get_dataset`` / ``generate_groundtruth``
(python/raft-ann-bench/src/raft_ann_bench/{get_dataset,generate_groundtruth})
and the big-ann binary formats it consumes (.fbin/.u8bin/.ibin: int32 count,
int32 dim, then row-major payload; hdf5 ann-benchmarks files with
train/test/neighbors/distances groups).

This environment has no network egress, so ``get_dataset``'s download step
is replaced by deterministic synthetic generators with the standard
million-scale shapes (sift-128, glove-100, …); files round-trip through the
same binary formats so externally fetched datasets drop in unchanged.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

import jax

from raft_tpu.core.resources import Resources, ensure
from raft_tpu.neighbors import brute_force

_DTYPES = {"fbin": np.float32, "u8bin": np.uint8, "i8bin": np.int8, "ibin": np.int32}


def write_bin(path: str, arr: np.ndarray) -> None:
    """big-ann binary writer: [n:int32][dim:int32][payload row-major].
    Memmap-backed inputs stream out in row chunks (100M-row slices never
    materialize in RAM)."""
    with open(path, "wb") as fh:
        fh.write(np.asarray(arr.shape, np.int32).tobytes())
        chunk = max(1, (1 << 28) // max(1, arr.shape[1] * arr.itemsize))
        for i in range(0, arr.shape[0], chunk):
            fh.write(np.ascontiguousarray(arr[i:i + chunk]).tobytes())


def read_bin(path: str, dtype=None, *, rows: Optional[int] = None,
             mmap: bool = False) -> np.ndarray:
    """Read a big-ann binary file. ``rows`` slices to the first ``rows``
    vectors without materializing the rest (memmap-backed); ``mmap=True``
    returns the mapping itself so billion-row files never enter RAM.
    ``dtype`` should be passed explicitly when ``path`` doesn't carry the
    big-ann extension (e.g. a ``.download`` temp name)."""
    if dtype is None:
        ext = path.rsplit(".", 1)[-1]
        dtype = _DTYPES.get(ext, np.float32)
    with open(path, "rb") as fh:
        n, dim = (int(x) for x in np.frombuffer(fh.read(8), np.int32))
    if rows is not None:
        n = min(n, int(rows))
    data = np.memmap(path, dtype, mode="r", offset=8, shape=(n, dim))
    return data if mmap else np.asarray(data).copy()


# --- TEXMEX .fvecs/.ivecs/.bvecs (sift/gist distributions: every row is
# [dim:int32][payload]) — the other standard ANN interchange format the
# reference's docs point users at (docs/source/raft_ann_benchmarks.md).

_VECS_DTYPES = {"fvecs": np.float32, "ivecs": np.int32, "bvecs": np.uint8}


def write_vecs(path: str, arr: np.ndarray) -> None:
    ext = path.rsplit(".", 1)[-1]
    dtype = _VECS_DTYPES[ext]
    arr = np.ascontiguousarray(arr, dtype)
    n, d = arr.shape
    dims = np.full((n, 1), d, np.int32)
    if dtype == np.uint8:
        rows = np.concatenate([dims.view(np.uint8).reshape(n, 4), arr], axis=1)
    else:
        rows = np.concatenate([dims.view(dtype), arr], axis=1)
    with open(path, "wb") as fh:
        fh.write(rows.tobytes())


def read_vecs(path: str) -> np.ndarray:
    ext = path.rsplit(".", 1)[-1]
    dtype = _VECS_DTYPES[ext]
    raw = np.fromfile(path, np.uint8)
    if raw.size == 0:
        return np.zeros((0, 0), dtype)
    d = int(np.frombuffer(raw[:4].tobytes(), np.int32)[0])
    itemsize = np.dtype(dtype).itemsize
    row_bytes = 4 + d * itemsize
    if raw.size % row_bytes:
        raise ValueError(f"{path}: size {raw.size} not a multiple of row {row_bytes}")
    rows = raw.reshape(-1, row_bytes)
    return (
        rows[:, 4:].reshape(-1).view(dtype).reshape(rows.shape[0], d).copy()
    )


def load_hdf5(path: str, name: str = "") -> "Dataset":
    """Read an ann-benchmarks HDF5 file (train/test/neighbors/distances
    groups). Requires ``h5py``; raises a clear error when absent (this image
    doesn't ship it — externally prepared files convert via write_bin)."""
    try:
        import h5py  # type: ignore
    except ImportError as e:  # pragma: no cover - h5py not in this image
        raise RuntimeError(
            "load_hdf5 requires h5py; convert the file to the big-ann .fbin "
            "layout (write_bin) on a machine that has it"
        ) from e
    with h5py.File(path, "r") as f:  # pragma: no cover - h5py not in image
        # h5py string attrs may come back as bytes (fixed-length storage)
        dist = f.attrs.get("distance", "euclidean")
        if isinstance(dist, bytes):
            dist = dist.decode()
        metric = {"euclidean": "sqeuclidean", "angular": "cosine"}.get(
            dist, "sqeuclidean"
        )
        ds = Dataset(
            name=name or os.path.basename(path),
            base=np.asarray(f["train"]),
            queries=np.asarray(f["test"]),
            metric=metric,
        )
        if "neighbors" in f:
            ds.gt_neighbors = np.asarray(f["neighbors"], np.int32)
        if "distances" in f:
            ds.gt_distances = np.asarray(f["distances"], np.float32)
        return ds


@dataclass
class Dataset:
    name: str
    base: np.ndarray        # [n, d]
    queries: np.ndarray     # [q, d]
    gt_neighbors: Optional[np.ndarray] = None   # [q, k]
    gt_distances: Optional[np.ndarray] = None
    metric: str = "sqeuclidean"


# standard dataset geometries (ref: docs/source/raft_ann_benchmarks.md:289-294
# million-scale suite + run/conf/*.json dataset blocks)
_SYNTH_SHAPES = {
    "sift-128-euclidean": (1_000_000, 128, 10_000, "sqeuclidean"),
    "glove-100-inner": (1_183_514, 100, 10_000, "inner_product"),
    "fashion-mnist-784-euclidean": (60_000, 784, 10_000, "sqeuclidean"),
    "nytimes-256-angular": (290_000, 256, 10_000, "cosine"),
    "mnist-784-euclidean": (60_000, 784, 10_000, "sqeuclidean"),
    "deep-image-96-inner": (9_990_000, 96, 10_000, "inner_product"),
}


def synthetic(
    name: str = "sift-128-euclidean",
    *,
    scale: float = 1.0,
    n_queries: int = 0,
    seed: int = 0,
    clustered: bool = True,
) -> Dataset:
    """Deterministic synthetic stand-in with a standard dataset's geometry.
    ``scale`` shrinks n for quick runs (scale=0.01 → 1% of the rows)."""
    if name not in _SYNTH_SHAPES:
        raise ValueError(f"unknown dataset {name}; have {sorted(_SYNTH_SHAPES)}")
    n, d, q, metric = _SYNTH_SHAPES[name]
    return synthetic_geometry(name, n, d, metric, scale=scale,
                              n_queries=n_queries, default_queries=q,
                              seed=seed, clustered=clustered)


def synthetic_geometry(
    name: str,
    n: int,
    d: int,
    metric: str,
    *,
    scale: float = 1.0,
    n_queries: int = 0,
    default_queries: int = 10_000,
    seed: int = 0,
    clustered: bool = True,
) -> Dataset:
    """Synthetic workload from explicit geometry — the path conf-driven
    runs take for datasets whose files are not on disk (the reference
    confs name e.g. deep-100M/base.1B.fbin; here the published dims and
    metric reproduce the workload shape).

    An explicit ``n_queries`` wins unclamped (callers like frontier.py
    request exact query counts); 0 scales ``default_queries`` down with
    small n."""
    n = max(1000, int(n * scale))
    q = n_queries or min(default_queries, max(100, n // 100))
    rng = np.random.default_rng(seed)
    if clustered:
        # mixture of gaussians — ANN-relevant structure (pure uniform data
        # has no cluster structure for IVF/graph indexes to exploit)
        n_clusters = max(16, int(np.sqrt(n) / 4))
        centers = rng.random((n_clusters, d), dtype=np.float32) * 10
        lab = rng.integers(0, n_clusters, n)
        base = centers[lab] + rng.normal(0, 1.0, (n, d)).astype(np.float32)
        qlab = rng.integers(0, n_clusters, q)
        queries = centers[qlab] + rng.normal(0, 1.0, (q, d)).astype(np.float32)
    else:
        base = rng.random((n, d), dtype=np.float32)
        queries = rng.random((q, d), dtype=np.float32)
    return Dataset(name=name, base=base, queries=queries, metric=metric)


#: base chunk uploaded per groundtruth pass; memmap/huge bases stream
#: through the device in pieces of this many bytes (float32-converted)
_GT_BASE_CHUNK_BYTES = 1 << 30


def generate_groundtruth(
    ds: Dataset, k: int = 100, *, batch: int = 2048,
    res: Optional[Resources] = None,
) -> Dataset:
    """Exact groundtruth via device brute force (ref: raft-ann-bench
    generate_groundtruth — it likewise runs pylibraft brute_force on GPU).
    Bases larger than ~1 GiB (e.g. the memmapped 100M-row big-ann slices)
    are streamed through the device in row chunks with a host-side top-k
    merge — the full base is never materialized on device."""
    res = ensure(res)
    import jax.numpy as jnp

    f32_bytes = ds.base.shape[0] * ds.base.shape[1] * 4
    if f32_bytes <= _GT_BASE_CHUNK_BYTES and not isinstance(ds.base, np.memmap):
        base = jnp.asarray(ds.base)
        dists, ids = [], []
        for s in range(0, ds.queries.shape[0], batch):
            v, i = brute_force.knn(
                base, jnp.asarray(ds.queries[s : s + batch]), k,
                metric=ds.metric, res=res,
            )
            dists.append(np.asarray(v))
            ids.append(np.asarray(i))
        ds.gt_distances = np.concatenate(dists)
        ds.gt_neighbors = np.concatenate(ids)
        return ds

    n, d = ds.base.shape
    rows = max(k, _GT_BASE_CHUNK_BYTES // (d * 4))
    largest = ds.metric == "inner_product"
    best_v = np.full((ds.queries.shape[0], k),
                     -np.inf if largest else np.inf, np.float32)
    best_i = np.full((ds.queries.shape[0], k), -1, np.int64)
    for cs in range(0, n, rows):
        chunk = jnp.asarray(np.ascontiguousarray(ds.base[cs:cs + rows],
                                                 dtype=np.float32))
        kk = min(k, int(chunk.shape[0]))
        for s in range(0, ds.queries.shape[0], batch):
            v, i = brute_force.knn(
                chunk, jnp.asarray(ds.queries[s:s + batch], dtype=jnp.float32),
                kk, metric=ds.metric, res=res,
            )
            cand_v = np.concatenate([best_v[s:s + batch], np.asarray(v)], 1)
            cand_i = np.concatenate(
                [best_i[s:s + batch], np.asarray(i).astype(np.int64) + cs], 1
            )
            key = -cand_v if largest else cand_v
            part = np.argpartition(key, k - 1, axis=1)[:, :k]
            order = np.argsort(np.take_along_axis(key, part, 1), 1)
            top = np.take_along_axis(part, order, 1)
            best_v[s:s + batch] = np.take_along_axis(cand_v, top, 1)
            best_i[s:s + batch] = np.take_along_axis(cand_i, top, 1)
    ds.gt_distances = best_v
    ds.gt_neighbors = best_i.astype(np.int32)
    return ds


#: big-ann extension for each storable vector dtype (reverse of _DTYPES)
_EXTS = {np.dtype(np.float32): "fbin", np.dtype(np.uint8): "u8bin",
         np.dtype(np.int8): "i8bin"}


def save(ds: Dataset, directory: str) -> None:
    """Persist in the big-ann layout raft-ann-bench uses
    (base.fbin / query.fbin / groundtruth.neighbors.ibin / ...distances.fbin).
    uint8/int8 bases (bigann) keep their dtype and get the matching
    extension (base.u8bin) so ``load``'s extension-driven dtype inference
    round-trips."""
    os.makedirs(directory, exist_ok=True)
    for stem, arr in (("base", ds.base), ("query", ds.queries)):
        ext = _EXTS.get(np.dtype(arr.dtype))
        if ext is None:  # anything non-standard stores as float32
            arr, ext = np.asarray(arr, np.float32), "fbin"
        write_bin(os.path.join(directory, f"{stem}.{ext}"), arr)
    if ds.gt_neighbors is not None:
        write_bin(
            os.path.join(directory, "groundtruth.neighbors.ibin"),
            ds.gt_neighbors.astype(np.int32),
        )
        write_bin(
            os.path.join(directory, "groundtruth.distances.fbin"),
            ds.gt_distances.astype(np.float32),
        )


def load(directory: str, name: str = "", metric: str = "sqeuclidean",
         *, mmap: bool = False) -> Dataset:
    """Load a dataset directory in either standard layout: big-ann
    (base.{fbin,u8bin,i8bin}/query.*/groundtruth.*.ibin) or TEXMEX
    (<name>_base.fvecs / _query.fvecs / _groundtruth.ivecs, the sift-1M
    distribution layout). ``mmap=True`` leaves the base on disk
    (100M-row directories load instantly and stream on use)."""
    base_path = next(
        (p for e in ("fbin", "u8bin", "i8bin")
         if os.path.exists(p := os.path.join(directory, f"base.{e}"))),
        None,
    )
    if base_path is None:
        import glob as _glob

        bases = sorted(_glob.glob(os.path.join(directory, "*_base.*vecs")))
        if bases:
            prefix = bases[0].rsplit("_base.", 1)[0]
            ext = bases[0].rsplit(".", 1)[-1]
            ds = Dataset(
                name=name or os.path.basename(prefix),
                base=read_vecs(f"{prefix}_base.{ext}"),
                queries=read_vecs(f"{prefix}_query.{ext}"),
                metric=metric,
            )
            gt = f"{prefix}_groundtruth.ivecs"
            if os.path.exists(gt):
                ds.gt_neighbors = read_vecs(gt).astype(np.int32)
            return ds
        raise FileNotFoundError(f"no base.{{fbin,u8bin,i8bin}} in {directory}")
    ext = base_path.rsplit(".", 1)[-1]
    ds = Dataset(
        name=name or os.path.basename(directory.rstrip("/")),
        base=read_bin(base_path, mmap=mmap),
        queries=read_bin(os.path.join(directory, f"query.{ext}")),
        metric=metric,
    )
    gtn = os.path.join(directory, "groundtruth.neighbors.ibin")
    if os.path.exists(gtn):
        ds.gt_neighbors = read_bin(gtn, np.int32)
        ds.gt_distances = read_bin(
            os.path.join(directory, "groundtruth.distances.fbin"), np.float32
        )
    return ds
