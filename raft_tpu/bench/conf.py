"""Reference-conf-driven bench orchestration (VERDICT r4 next #8).

Accepts the reference's per-dataset JSON configs *unmodified* —
``python -m raft_tpu.bench --conf run/conf/deep-100M.json`` — and
translates them to this repo's runner config shape
(ref: python/raft-ann-bench/src/raft_ann_bench/run/conf/*.json, the
orchestration of run/__main__.py:115-190, and each GPU wrapper's
param parser: bench/ann/src/raft/raft_benchmark.cu
``parse_build_param``/``parse_search_param``).

The reference conf names GPU implementations (raft_ivf_pq,
faiss_gpu_ivf_flat, ggnn, hnswlib, ...).  Mapping policy:

* ``raft_*`` / ``faiss_*`` IVF and CAGRA entries translate to the
  TPU-native equivalents with their tuning grids intact (nlist→n_lists,
  nprobe→n_probes, M→pq_dim, ratio→1/kmeans_trainset_fraction, ...).
* ``hnswlib`` maps to the from-scratch native HNSW engine when an
  exported index exists; otherwise it is skipped and reported — there is
  no CPU hnswlib in this image (VERDICT r4 weak #7).
* Unknown algos are skipped and reported, never silently dropped.

Dataset sections name on-disk files (base_file/query_file).  When the
files exist (datasets.get_dataset fetched them) they are loaded;
otherwise a synthetic workload with the dataset's published geometry is
generated, scaled by ``--scale`` — the judged TPU runs use synthetic
DEEP-shaped data (BASELINE.md posture).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

#: published geometry of the reference's conf datasets: dims, metric
#: (ref: run/conf/*.json "dataset" sections + datasets.yaml)
_REF_DATASET_GEOMETRY = {
    "deep-100M": (96, "sqeuclidean"),
    "deep-1B": (96, "sqeuclidean"),
    "deep-image-96-inner": (96, "inner_product"),
    "bigann-100M": (128, "sqeuclidean"),
    "sift-128-euclidean": (128, "sqeuclidean"),
    "glove-100-inner": (100, "inner_product"),
    "glove-100-angular": (100, "cosine"),
    "nytimes-256-angular": (256, "cosine"),
    "fashion-mnist-784-euclidean": (784, "sqeuclidean"),
    "mnist-784-euclidean": (784, "sqeuclidean"),
    "wiki_all_1M": (768, "inner_product"),
    "wiki_all_10M": (768, "inner_product"),
    "wiki_all_88M": (768, "inner_product"),
    "lastfm-65-angular": (65, "cosine"),
}

_REF_METRIC = {"euclidean": "sqeuclidean", "inner_product": "inner_product",
               "angular": "cosine", "cosine": "cosine"}


def _ratio_to_fraction(bp: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    if "niter" in bp:
        out["kmeans_n_iters"] = int(bp["niter"])
    if "ratio" in bp:
        # ref raft_benchmark.cu parse_build_param:
        # kmeans_trainset_fraction = 1.0 / ratio
        out["kmeans_trainset_fraction"] = 1.0 / float(bp["ratio"])
    return out


def _map_ivf_flat(bp: Dict[str, Any]) -> Dict[str, Any]:
    return {"n_lists": int(bp["nlist"]), **_ratio_to_fraction(bp)}


def _map_ivf_pq(bp: Dict[str, Any],
                search_params: List[Dict[str, Any]]) -> Dict[str, Any]:
    out = {"n_lists": int(bp["nlist"]), **_ratio_to_fraction(bp)}
    # raft confs say pq_dim; faiss confs say M (same quantity)
    if "pq_dim" in bp:
        out["pq_dim"] = int(bp["pq_dim"])
    elif "M" in bp:
        out["pq_dim"] = int(bp["M"])
    if "pq_bits" in bp:
        out["pq_bits"] = int(bp["pq_bits"])
    # the reference tunes the search-side LUT dtype (smemLutDtype); the
    # TPU design's analogous knob is the build-side decoded-cache dtype —
    # honor a half/fp8 request with the matching cache rung
    luts = {sp.get("smemLutDtype", sp.get("internalDistanceDtype", ""))
            for sp in search_params}
    if "fp8" in luts:
        out["decoded_dtype"] = "int8"
    elif "half" in luts:
        out["decoded_dtype"] = "bfloat16"
    return out


def _map_cagra(bp: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    if "graph_degree" in bp:
        out["graph_degree"] = int(bp["graph_degree"])
    if "intermediate_graph_degree" in bp:
        out["intermediate_graph_degree"] = int(bp["intermediate_graph_degree"])
    return out


def _map_ivf_search(sp: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    if "nprobe" in sp:
        out["n_probes"] = int(sp["nprobe"])
    if "refine_ratio" in sp:
        rr = int(float(sp["refine_ratio"]))
        if rr > 1:
            out["refine_ratio"] = rr
    return out


def _map_cagra_search(sp: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    if "itopk" in sp:
        out["itopk_size"] = int(sp["itopk"])
    if "search_width" in sp:
        out["search_width"] = int(sp["search_width"])
    if "max_iterations" in sp:
        out["max_iterations"] = int(sp["max_iterations"])
    return out


def translate(conf: Dict[str, Any], *, algo_filter: Optional[set] = None
              ) -> Tuple[Dict[str, Any], Dict[str, Any], List[str]]:
    """Reference conf → (dataset_info, runner config, skipped notes).

    dataset_info: {"name", "dims", "metric", "subset_size", "k",
    "batch_size", "base_file", "query_file"} — dims/metric resolved from
    the published geometry table (falling back to the conf's "distance").
    """
    ds = conf.get("dataset", {})
    name = ds.get("name", "unknown")
    geom = _REF_DATASET_GEOMETRY.get(name)
    metric = _REF_METRIC.get(ds.get("distance", ""), None)
    if geom:
        dims, geom_metric = geom
        metric = metric or geom_metric
    else:
        dims = int(ds.get("dims", 0))
        if not dims:
            raise ValueError(
                f"dataset {name!r} not in the geometry table and the conf "
                "carries no dims; add it to _REF_DATASET_GEOMETRY")
        metric = metric or "sqeuclidean"
    info = {
        "name": name,
        "dims": dims,
        "metric": metric,
        "subset_size": int(ds.get("subset_size", 0)),
        "k": int(conf.get("search_basic_param", {}).get("k", 10)),
        "batch_size": int(
            conf.get("search_basic_param", {}).get("batch_size", 10_000)),
        "base_file": ds.get("base_file", ""),
        "query_file": ds.get("query_file", ""),
        "groundtruth_file": ds.get("groundtruth_neighbors_file", ""),
    }

    algos, skipped = [], []
    for entry in conf.get("index", []):
        algo = entry.get("algo", "")
        ename = entry.get("name", algo)
        if algo_filter is not None and ename not in algo_filter \
                and algo not in algo_filter:
            continue
        bp = entry.get("build_param", {})
        sps = entry.get("search_params", [{}])
        try:
            if algo.endswith("ivf_flat"):
                algos.append({
                    "name": "raft_tpu_ivf_flat",
                    "label": ename,
                    "build_param": _map_ivf_flat(bp),
                    "search_params": [_map_ivf_search(s) for s in sps],
                })
            elif algo.endswith("ivf_pq"):
                algos.append({
                    "name": "raft_tpu_ivf_pq",
                    "label": ename,
                    "build_param": _map_ivf_pq(bp, sps),
                    "search_params": [_map_ivf_search(s) for s in sps],
                })
            elif algo.endswith("cagra"):
                algos.append({
                    "name": "raft_tpu_cagra",
                    "label": ename,
                    "build_param": _map_cagra(bp),
                    "search_params": [_map_cagra_search(s) for s in sps],
                })
            elif algo == "ggnn":
                skipped.append(f"{ename}: ggnn is CUDA-only; the graph "
                               "family maps to raft_tpu_cagra entries")
            elif algo == "hnswlib":
                skipped.append(f"{ename}: no CPU hnswlib in this image; "
                               "the native engine benches exported indexes "
                               "(bench.runner hnsw_native)")
            else:
                skipped.append(f"{ename}: unknown algo {algo!r}")
        except KeyError as e:  # a param the mapper requires is missing
            skipped.append(f"{ename}: missing build param {e}")
    return info, {"algos": algos}, skipped


def load(path: str, *, algo_filter: Optional[set] = None):
    """Load a reference-shaped conf file and translate it."""
    with open(path) as fh:
        conf = json.load(fh)
    if "index" not in conf:
        raise ValueError(
            f"{os.path.basename(path)} is not a reference-shaped conf "
            "(no top-level 'index' list)")
    return translate(conf, algo_filter=algo_filter)


# ---- per-algo YAML tuning grids (ref: run/conf/algos/*.yaml + the
# cartesian expansion of run/__main__.py; constraints modules prune
# infeasible combos — here the TPU-relevant feasibility rules inline) ----

def _product(grid: Dict[str, list]) -> List[Dict[str, Any]]:
    keys = sorted(grid)
    out: List[Dict[str, Any]] = [{}]
    for key in keys:
        vals = grid[key]
        if not isinstance(vals, list):
            vals = [vals]
        out = [{**d, key: v} for d in out for v in vals]
    return out


def _build_feasible(algo: str, bp: Dict[str, Any], dims: int, n: int) -> bool:
    """The role of the reference's constraints module
    (raft_ann_bench.constraints.raft_ivf_pq_build_constraints: pq_dim
    bounds vs dims); plus the hard n_lists <= n rule."""
    if bp.get("nlist", 1) > max(1, n):
        return False
    pq_dim = bp.get("pq_dim", bp.get("M", 0))
    if pq_dim and dims and pq_dim > dims:
        return False
    return True


def load_algo_yaml(path: str, *, group: str = "base",
                   dataset_info: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """One algos/*.yaml tuning grid → runner config: the named group's
    build grid expands to one entry per build combo (cartesian), each
    carrying the group's expanded search grid — the reference's
    run/__main__ semantics.  Infeasible combos prune silently (the
    constraints-module role); the caller's dataset decides dims/n."""
    import yaml

    with open(path) as fh:
        doc = yaml.safe_load(fh)
    name = doc.get("name", "unknown")
    groups = doc.get("groups", {})
    if group not in groups:
        raise ValueError(
            f"{name} has no group {group!r}; available: {sorted(groups)}")
    g = groups[group]
    dims = int((dataset_info or {}).get("dims", 0))
    n = int((dataset_info or {}).get("subset_size", 0)) or (1 << 62)
    builds = [bp for bp in _product(g.get("build", {}))
              if _build_feasible(name, bp, dims, n)]
    searches = _product(g.get("search", {}))
    entries = []
    for bp in builds:
        label = name + "." + "-".join(
            f"{k}{bp[k]}" for k in sorted(bp))
        entries.append({"name": name, "algo": name,
                        "build_param": bp, "search_params": searches,
                        "file": label})
    # reuse the JSON-conf translator for the name/param mapping
    info = dataset_info or {"name": "unknown", "dims": dims,
                            "subset_size": 0}
    conf = {"dataset": {"name": info.get("name", "unknown"),
                        # carry dims so translate() never depends on the
                        # built-in geometry table for registry datasets
                        "dims": dims,
                        "distance": {"sqeuclidean": "euclidean"}.get(
                            info.get("metric", ""), info.get("metric", "")),
            },
            "search_basic_param": {"k": info.get("k", 10)},
            "index": [{**e, "name": e["file"]} for e in entries]}
    _, cfg, skipped = translate(conf)
    return {"algos": cfg["algos"], "skipped": skipped}


def load_datasets_yaml(path: str) -> Dict[str, Dict[str, Any]]:
    """run/conf/datasets.yaml → {name: dataset_info} (the geometry +
    file-name registry the reference ships)."""
    import yaml

    with open(path) as fh:
        docs = yaml.safe_load(fh)
    out = {}
    for d in docs or []:
        name = d.get("name")
        if not name:
            continue
        out[name] = {
            "name": name,
            "dims": int(d.get("dims", 0) or
                        _REF_DATASET_GEOMETRY.get(name, (0, ""))[0]),
            "metric": _REF_METRIC.get(d.get("distance", ""), "sqeuclidean"),
            "subset_size": int(d.get("subset_size", 0)),
            "base_file": d.get("base_file", ""),
            "query_file": d.get("query_file", ""),
            "groundtruth_file": d.get("groundtruth_neighbors_file", ""),
            "k": 10,
        }
    return out
