"""BASELINE ladder runner — configs #1–#4 with QPS@recall, GB/s, and MFU.

Reference: the raft-ann-bench harness records QPS/latency/recall as
first-class counters (cpp/bench/ann/src/common/benchmark.hpp:330-379);
BASELINE.md defines the measurable ladder for this repo:

  #1 pairwise L2 1k×128 — correctness vs numpy + bandwidth
  #2 brute-force kNN (SIFT-10k shape) — recall 1.0 + GB/s + GFLOP/s
  #3 IVF-Flat (SIFT-1M shape) — QPS @ recall ≥ 0.95
  #4 IVF-PQ + CAGRA (DEEP/GIST shape) — QPS @ recall ≥ 0.95 (north star)

Usage:
    python -m raft_tpu.bench.ladder [--scale 1.0] [--out benchmarks/...]

Results append to a JSON file (default ``benchmarks/ladder_<platform>.json``)
with one record per config: metric values, operating point, achieved
FLOP/s ÷ peak (MFU) and HBM GB/s where computable. Wall-clock through the
axon tunnel overstates absolute rates (see .claude/skills/verify) — MFU/GB/s
are recorded for trend tracking, not as absolute hardware truth. Dispatch
latency (~75 ms measured) is amortized with large query batches.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

if os.environ.get("RAFT_TPU_PLATFORM"):  # raft-tpu: ignore[ENVREG] pre-jax bootstrap
    import jax

    jax.config.update("jax_platforms", os.environ["RAFT_TPU_PLATFORM"])  # raft-tpu: ignore[ENVREG] pre-jax bootstrap

# chip peaks for MFU accounting (per public TPU specs); fallback None → MFU
# omitted on unknown platforms
_PEAKS = {
    # TPU v5e: 197 TFLOP/s bf16, 819 GB/s HBM
    "tpu": {"flops_bf16": 197e12, "flops_f32": 98.5e12, "hbm_gbs": 819.0},
}


def _timeit(fn, *args, warmup=2, iters=5):
    import jax

    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def _dev(fn, *args):
    """Device busy time for one call (None on host-only backends) — the
    reference's CUDA-event GPU-time counter (benchmark.hpp:165,330-333)."""
    from raft_tpu.bench.device_time import measure_device_time

    return measure_device_time(fn, *args)


def _blobs(n, d, n_clusters, seed):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((n_clusters, d)).astype(np.float32)
    asg = rng.integers(0, n_clusters, n)
    return centers, (
        centers[asg] + rng.standard_normal((n, d)).astype(np.float32) * 0.35
    )


def _recall(ids, gt):
    from raft_tpu.stats import neighborhood_recall

    return float(neighborhood_recall(np.asarray(ids), np.asarray(gt)))


def config1_pairwise(res, platform):
    import jax.numpy as jnp

    from raft_tpu.distance.pairwise import pairwise_distance

    rng = np.random.default_rng(0)
    x = rng.standard_normal((1000, 128)).astype(np.float32)
    y = rng.standard_normal((1000, 128)).astype(np.float32)
    got = np.asarray(pairwise_distance(jnp.asarray(x), jnp.asarray(y), metric="sqeuclidean"))
    want = ((x[:, None] - y[None]) ** 2).sum(-1)
    max_rel = float(np.max(np.abs(got - want) / np.maximum(want, 1e-6)))
    s = _timeit(
        lambda a, b: pairwise_distance(a, b, metric="sqeuclidean", res=res),
        jnp.asarray(x), jnp.asarray(y),
    )
    bytes_moved = (2 * 1000 * 128 + 1000 * 1000) * 4
    return {
        "config": "1_pairwise_l2_1kx128",
        "max_rel_err_vs_numpy": max_rel,
        "seconds": s,
        "gbs": bytes_moved / s / 1e9,
        "pass": max_rel < 1e-4,
    }


def config2_bruteforce(res, platform, scale):
    import jax.numpy as jnp

    from raft_tpu.neighbors import brute_force

    n, d, n_q, k = int(10_000 * scale), 128, int(1_000 * scale), 10
    rng = np.random.default_rng(1)
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((n_q, d)).astype(np.float32)
    xd, qd = jnp.asarray(x), jnp.asarray(q)
    _, ids = brute_force.knn(xd, qd, k, res=res)
    # exact numpy ground truth
    d2 = ((q[:, None] - x[None]) ** 2).sum(-1) if n * n_q <= 2e7 else None
    if d2 is not None:
        gt = np.argsort(d2, axis=1)[:, :k]
        recall = _recall(ids, gt)
    else:
        recall = None
    s = _timeit(lambda a, b: brute_force.knn(a, b, k, res=res), xd, qd)
    dev_s = _dev(lambda a, b: brute_force.knn(a, b, k, res=res), xd, qd)
    flops = 2.0 * n * n_q * d
    peaks = _PEAKS.get(platform)
    return {
        "config": "2_bruteforce_sift10k",
        "n": n,
        "recall": recall,
        "qps": n_q / s,
        "device_seconds": dev_s,
        "device_qps": n_q / dev_s if dev_s else None,
        "gflops": flops / s / 1e9,
        "mfu_f32": (flops / s) / peaks["flops_f32"] if peaks else None,
        "pass": recall is None or recall >= 0.999,
    }


def config3_ivf_flat(res, platform, scale):
    import jax.numpy as jnp

    from raft_tpu.neighbors import brute_force, ivf_flat

    n, d, n_q, k = int(1_000_000 * scale), 128, int(10_000 * scale), 10
    n = max(n, 20_000)
    n_q = max(n_q, 200)
    n_clusters = max(64, n // 250)  # ~250 rows/cluster at any scale
    c, x = _blobs(n, d, n_clusters, 2)
    rng_q = np.random.default_rng(3)
    q = (
        c[rng_q.integers(0, n_clusters, n_q)]
        + rng_q.standard_normal((n_q, d)).astype(np.float32) * 0.35
    )
    xd, qd = jnp.asarray(x), jnp.asarray(q)
    t0 = time.perf_counter()
    index = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=max(64, int(np.sqrt(n) * 2)), kmeans_n_iters=10),
        xd, res=res,
    )
    build_s = time.perf_counter() - t0
    _, gt = brute_force.knn(xd, qd, k, res=res)
    best = None
    for p in (8, 16, 32, 64, 128):
        if p > index.n_lists:
            break
        sp = ivf_flat.SearchParams(n_probes=p)
        _, ids = ivf_flat.search(sp, index, qd, k, res=res)
        r = _recall(ids, gt)
        s = _timeit(lambda qq: ivf_flat.search(sp, index, qq, k, res=res), qd)
        best = {"n_probes": p, "recall": r, "qps": n_q / s}
        if r >= 0.95:
            break
    dev_s = _dev(lambda qq: ivf_flat.search(sp, index, qq, k, res=res), qd)
    best["device_seconds"] = dev_s
    best["device_qps"] = n_q / dev_s if dev_s else None
    # bandwidth: probed rows streamed per query batch
    row_bytes = d * np.dtype(np.float32).itemsize
    scanned = n_q * best["n_probes"] * index.list_cap * row_bytes
    peaks = _PEAKS.get(platform)
    return {
        "config": "3_ivf_flat_sift1m",
        "n": n,
        "build_s": build_s,
        **best,
        "scan_gbs": scanned * best["qps"] / n_q / 1e9,
        "hbm_frac": (scanned * best["qps"] / n_q) / (peaks["hbm_gbs"] * 1e9)
        if peaks
        else None,
        "pass": best["recall"] >= 0.9,
    }


def config4_ivf_pq_cagra(res, platform, scale):
    import jax.numpy as jnp

    from raft_tpu.neighbors import brute_force, cagra, ivf_pq
    from raft_tpu.neighbors.refine import refine

    n, d, n_q, k = int(100_000 * scale), 96, int(10_000 * scale), 10
    n = max(n, 20_000)
    n_q = max(n_q, 200)
    n_clusters = max(64, n // 100)
    c, x = _blobs(n, d, n_clusters, 4)
    rng_q = np.random.default_rng(5)
    q = (
        c[rng_q.integers(0, n_clusters, n_q)]
        + rng_q.standard_normal((n_q, d)).astype(np.float32) * 0.35
    )
    xd, qd = jnp.asarray(x), jnp.asarray(q)
    _, gt = brute_force.knn(xd, qd, k, res=res)

    t0 = time.perf_counter()
    pq = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=1024, pq_dim=d // 2, kmeans_n_iters=10),
        xd, res=res,
    )
    pq_build_s = time.perf_counter() - t0
    pq_best = None
    for p in (8, 16, 32, 64, 128, 256):
        sp = ivf_pq.SearchParams(n_probes=p, lut_dtype="bfloat16")

        def fn(qq):
            _, ci = ivf_pq.search(sp, pq, qq, k * 4, res=res)
            return refine(xd, qq, ci, k, res=res)

        _, ids = fn(qd)
        r = _recall(ids, gt)
        s = _timeit(fn, qd)
        pq_best = {"n_probes": p, "recall": r, "qps": n_q / s}
        if r >= 0.95:
            break
    dev_s = _dev(fn, qd)
    pq_best["device_seconds"] = dev_s
    pq_best["device_qps"] = n_q / dev_s if dev_s else None

    t0 = time.perf_counter()
    cg = cagra.build(cagra.IndexParams(graph_degree=64), xd, res=res)
    cg_build_s = time.perf_counter() - t0
    cg_best = None
    # entry-seeded w=1 ladder: walk max_iterations up until the recall
    # gate clears (the round-4 winning region; itopk rises as a fallback)
    for itopk, mi in ((16, 3), (16, 4), (16, 6), (16, 8), (32, 8),
                      (32, 16), (64, 0)):
        sp = cagra.SearchParams(
            itopk_size=itopk, search_width=1, max_iterations=mi,
            num_entry_centers=16,
        )
        _, ids = cagra.search(sp, cg, qd, k, res=res)
        r = _recall(ids, gt)
        s = _timeit(lambda qq: cagra.search(sp, cg, qq, k, res=res), qd)
        cg_best = {"itopk": itopk, "max_iterations": mi, "recall": r,
                   "qps": n_q / s}
        if r >= 0.95:
            break
    dev_s = _dev(lambda qq: cagra.search(sp, cg, qq, k, res=res), qd)
    cg_best["device_seconds"] = dev_s
    cg_best["device_qps"] = n_q / dev_s if dev_s else None

    return {
        "config": "4_ivf_pq_cagra_deep100k",
        "n": n,
        "ivf_pq": {"build_s": pq_build_s, **pq_best},
        "cagra": {"build_s": cg_build_s, **cg_best},
        "pass": pq_best["recall"] >= 0.9 and cg_best["recall"] >= 0.85,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scale", type=float, default=1.0,
                    help="shrink factor for CPU smoke runs (e.g. 0.02)")
    ap.add_argument("--out", default="")
    ap.add_argument("--configs", default="1,2,3,4")
    args = ap.parse_args()

    import jax

    from raft_tpu.core.resources import Resources

    platform = jax.devices()[0].platform
    res = Resources(workspace_limit_bytes=1 << 30)
    out_path = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "benchmarks", f"ladder_{platform}.json",
    )
    os.makedirs(os.path.dirname(out_path), exist_ok=True)

    def mark_scaled(rec):
        """A pass at reduced scale is NOT a pass of the BASELINE config:
        stamp it "scaled" and put the effective n in the config name so a
        down-scaled run can never masquerade as the real ladder result."""
        if args.scale < 1.0:
            if "n" in rec:
                rec["config"] = f"{rec['config']}@n{rec['n']}"
            if rec.get("pass") is True and "n" in rec:
                rec["pass"] = "scaled"
        return rec

    wanted = set(args.configs.split(","))
    records = []
    if "1" in wanted:
        records.append(config1_pairwise(res, platform))
        print(json.dumps(records[-1]))
    if "2" in wanted:
        records.append(mark_scaled(config2_bruteforce(res, platform, args.scale)))
        print(json.dumps(records[-1]))
    if "3" in wanted:
        records.append(mark_scaled(config3_ivf_flat(res, platform, args.scale)))
        print(json.dumps(records[-1]))
    if "4" in wanted:
        records.append(mark_scaled(config4_ivf_pq_cagra(res, platform, args.scale)))
        print(json.dumps(records[-1]))

    doc = {"platform": platform, "scale": args.scale,
           "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"), "records": records}
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
