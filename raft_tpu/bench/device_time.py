"""Device-side timing for bench counters (ref: the reference's gbench
driver reports GPU time from CUDA events next to wall time,
cpp/bench/ann/src/common/benchmark.hpp:165,330-333).

The JAX analog: capture a ``jax.profiler`` trace around the measured
calls and sum the device-plane event durations from the ``*.xplane.pb``
dump. The dump is a TensorFlow-profiler XSpace protobuf; TF isn't in the
image, so a ~60-line protobuf *wire* parser extracts just what the
counter needs (plane name, line events, event durations) — the schema is
stable and public (tsl/profiler/protobuf/xplane.proto: XSpace.planes=1;
XPlane.name=2,.lines=3; XLine.events=4; XEvent.offset_ps=2,
.duration_ps=3 — field numbers verified against a live dump in
tests/test_bench.py::TestDeviceTime).

On host-only backends (CPU fallback) the profiler emits no ``/device:``
plane and :func:`measure_device_time` returns None — callers report the
counter as null rather than faking it with wall time.
"""

from __future__ import annotations

import glob
import os
import shutil
import tempfile
from typing import Dict, Iterator, Optional, Tuple, Union

_Field = Tuple[int, Union[int, bytes]]


def _varint(b: bytes, i: int) -> Tuple[int, int]:
    r = s = 0
    while True:
        x = b[i]
        i += 1
        r |= (x & 0x7F) << s
        if not x & 0x80:
            return r, i
        s += 7


def _fields(b: bytes) -> Iterator[_Field]:
    """Iterate (field_number, value) over one protobuf message's wire
    bytes; varints decode to int, length-delimited fields to bytes,
    fixed32/64 skipped (unused by the XSpace subset we read)."""
    i, end = 0, len(b)
    while i < end:
        tag, i = _varint(b, i)
        fn, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _varint(b, i)
            yield fn, v
        elif wt == 2:
            ln, i = _varint(b, i)
            yield fn, b[i:i + ln]
            i += ln
        elif wt == 5:
            i += 4
        elif wt == 1:
            i += 8
        else:  # wire types 3/4 (groups) never appear in xplane dumps
            raise ValueError(f"unsupported wire type {wt}")


def _line_busy_ps(line: bytes) -> int:
    """Sum of XEvent.duration_ps over one XLine."""
    busy = 0
    for fn, v in _fields(line):
        if fn == 4 and isinstance(v, bytes):        # XLine.events
            for fe, ve in _fields(v):
                if fe == 3 and isinstance(ve, int):  # XEvent.duration_ps
                    busy += ve
    return busy


def plane_busy_ps(xplane_pb: bytes) -> Dict[str, int]:
    """plane name → busy picoseconds (max over the plane's lines of the
    per-line event-duration sum — the busiest executor lane, which for a
    serially-executing accelerator equals elapsed device time the way
    CUDA events measure it)."""
    out: Dict[str, int] = {}
    for fn, v in _fields(xplane_pb):
        if fn != 1 or not isinstance(v, bytes):      # XSpace.planes
            continue
        name, busiest = "", 0
        for fp, vp in _fields(v):
            if fp == 2 and isinstance(vp, bytes):    # XPlane.name
                name = vp.decode("utf-8", "replace")
            elif fp == 3 and isinstance(vp, bytes):  # XPlane.lines
                busiest = max(busiest, _line_busy_ps(vp))
        out[name] = busiest
    return out


def device_busy_seconds(log_dir: str) -> Optional[float]:
    """Device busy time recorded under a ``jax.profiler.trace`` log dir,
    or None when no device plane exists (host-only backend).  Busiest
    device plane, not the sum: one chip dumps several "/device:" planes
    (compute plus DMA/non-core lanes), and summing them double-counted
    overlap — round-4's on-chip ladder showed device time exceeding wall
    time, which is impossible for a single invocation.

    Multi-device semantics: across several chips the max-over-planes is
    the busiest single chip's busy time — a wall-clock-like QPS
    denominator for SPMD work (all chips run the same program in
    lockstep), NOT aggregate device work.  Do not read it as total busy
    seconds across the fleet; for per-chip accounting group planes by
    device ordinal instead."""
    dumps = glob.glob(
        os.path.join(log_dir, "**", "*.xplane.pb"), recursive=True
    )
    busiest_ps = 0
    seen_device = False
    for path in dumps:
        with open(path, "rb") as fh:
            planes = plane_busy_ps(fh.read())
        for name, ps in planes.items():
            if name.startswith("/device:"):
                seen_device = True
                busiest_ps = max(busiest_ps, ps)
    return busiest_ps / 1e12 if seen_device else None


def measure_device_time(fn, *args) -> Optional[float]:
    """Run ``fn(*args)`` once under a profiler trace and return its device
    busy time in seconds (None on host-only backends or when the profiler
    is unavailable). The call is synchronized before and after so the
    trace contains exactly one invocation."""
    import jax

    tmp = tempfile.mkdtemp(prefix="raft_tpu_devtime_")
    try:
        jax.block_until_ready(args)
        try:
            trace = jax.profiler.trace(tmp)
            trace.__enter__()
        except Exception:
            # profiler unavailable (e.g. a second concurrent trace) — the
            # counter degrades to null; a failure of fn itself must NOT be
            # swallowed into the same null, so only the setup is guarded
            return None
        try:
            jax.block_until_ready(fn(*args))
        finally:
            trace.__exit__(None, None, None)
        return device_busy_seconds(tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
