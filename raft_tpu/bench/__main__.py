"""CLI: ``python -m raft_tpu.bench --dataset sift-128-euclidean --scale 0.01``
(ref: ``python -m raft_ann_bench.run`` orchestrator CLI,
run/__main__.py:115-190)."""

from __future__ import annotations

import argparse
import json
import os
import sys

# platform override must land before any backend is initialized (this image
# pre-imports jax with the TPU platform forced; jax.config still wins if no
# backend has been touched yet)
if os.environ.get("RAFT_TPU_PLATFORM"):
    import jax

    jax.config.update("jax_platforms", os.environ["RAFT_TPU_PLATFORM"])

from raft_tpu.bench import datasets, export, plot, runner

DEFAULT_CONFIG = {
    "algos": [
        {"name": "raft_tpu_brute_force", "build_param": {}, "search_params": [{}]},
        {
            "name": "raft_tpu_ivf_flat",
            "build_param": {"n_lists": 256},
            "search_params": [{"n_probes": p} for p in (8, 16, 32, 64)],
        },
        {
            "name": "raft_tpu_ivf_pq",
            "build_param": {"n_lists": 256, "pq_bits": 8},
            "search_params": [
                {"n_probes": p, "refine_ratio": r}
                for p in (8, 32) for r in (1, 2)
            ],
        },
        {
            "name": "raft_tpu_cagra",
            "build_param": {"graph_degree": 32, "intermediate_graph_degree": 64},
            "search_params": [{"itopk_size": t} for t in (32, 64, 128)],
        },
    ]
}


def main(argv=None):
    ap = argparse.ArgumentParser("raft_tpu.bench")
    ap.add_argument("--dataset", default="sift-128-euclidean")
    ap.add_argument("--scale", type=float, default=0.01,
                    help="fraction of the standard dataset size to generate")
    ap.add_argument("--config", default="", help="JSON config path")
    ap.add_argument("-k", type=int, default=10)
    ap.add_argument("--out", default="bench_results")
    ap.add_argument("--algorithms", default="",
                    help="comma-separated filter over config algos")
    args = ap.parse_args(argv)

    config = (
        json.load(open(args.config)) if args.config else DEFAULT_CONFIG
    )
    if args.algorithms:
        keep = set(args.algorithms.split(","))
        config = {"algos": [a for a in config["algos"] if a["name"] in keep]}

    ds = datasets.synthetic(args.dataset, scale=args.scale)
    datasets.generate_groundtruth(ds, k=max(args.k, 100))
    results = runner.run_config(ds, config, k=args.k)

    os.makedirs(args.out, exist_ok=True)
    base = os.path.join(args.out, f"{args.dataset}")
    runner.save_results(results, base + ".json")
    export.to_csv(results, base + ".csv")
    try:
        plot.plot_results(results, base + ".png")
    except Exception as e:  # plotting is best-effort (headless variations)
        print(f"plot skipped: {e}", file=sys.stderr)
    for r in results:
        print(
            f"{r.algo:24s} recall={r.recall:.4f} qps={r.qps:10.1f} "
            f"latency={r.latency_ms:.3f}ms build={r.build_time_s:.1f}s "
            f"{r.search_param}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
