"""CLI: ``python -m raft_tpu.bench --dataset sift-128-euclidean --scale 0.01``
(ref: ``python -m raft_ann_bench.run`` orchestrator CLI,
run/__main__.py:115-190)."""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

# platform override must land before any backend is initialized (this image
# pre-imports jax with the TPU platform forced; jax.config still wins if no
# backend has been touched yet)
if os.environ.get("RAFT_TPU_PLATFORM"):  # raft-tpu: ignore[ENVREG] pre-jax bootstrap
    import jax

    jax.config.update("jax_platforms", os.environ["RAFT_TPU_PLATFORM"])  # raft-tpu: ignore[ENVREG] pre-jax bootstrap

from raft_tpu.bench import datasets, export, plot, runner
from raft_tpu.core import env as _env

DEFAULT_CONFIG = {
    "algos": [
        {"name": "raft_tpu_brute_force", "build_param": {}, "search_params": [{}]},
        {
            "name": "raft_tpu_ivf_flat",
            "build_param": {"n_lists": 256},
            "search_params": [{"n_probes": p} for p in (8, 16, 32, 64)],
        },
        {
            "name": "raft_tpu_ivf_pq",
            "build_param": {"n_lists": 256, "pq_bits": 8},
            "search_params": [
                {"n_probes": p, "refine_ratio": r}
                for p in (8, 32) for r in (1, 2)
            ],
        },
        {
            "name": "raft_tpu_cagra",
            "build_param": {"graph_degree": 32, "intermediate_graph_degree": 64},
            "search_params": [{"itopk_size": t} for t in (32, 64, 128)],
        },
    ]
}


def _conf_dataset(info, args):
    """Dataset for a conf/yaml-driven run: the registry's on-disk big-ann
    files when present under --data-dir (memmapped, --scale slices rows),
    else a synthetic workload with the published geometry."""
    base_path = os.path.join(args.data_dir, info["base_file"]) \
        if info.get("base_file") else ""
    if base_path and os.path.exists(base_path):
        rows = info.get("subset_size") or None
        if rows and args.scale < 1.0:
            rows = max(1000, int(rows * args.scale))
            print(f"scale={args.scale}: using first {rows} rows of "
                  f"{info['base_file']}", file=sys.stderr)
        ds = datasets.Dataset(
            name=info["name"],
            base=datasets.read_bin(base_path, rows=rows, mmap=True),
            queries=datasets.read_bin(
                os.path.join(args.data_dir, info["query_file"])),
            metric=info["metric"],
        )
        # the conf's published groundtruth (ibin) saves the exact-kNN
        # regeneration — hours at 100M — but only at FULL scale: a row
        # slice changes the true neighbors
        gt = info.get("groundtruth_file", "")
        gt_path = os.path.join(args.data_dir, gt) if gt else ""
        if gt_path and os.path.exists(gt_path) and rows == (
                info.get("subset_size") or rows):
            gt_arr = datasets.read_bin(gt_path, dtype=np.int32)
            if gt_arr.shape[0] == ds.queries.shape[0]:
                ds.gt_neighbors = gt_arr
                print(f"loaded groundtruth from {gt}", file=sys.stderr)
            else:  # stale/truncated file: regenerate instead of a
                # broadcast failure (or bogus recall) mid-sweep
                print(f"groundtruth rows {gt_arr.shape[0]} != queries "
                      f"{ds.queries.shape[0]}; regenerating",
                      file=sys.stderr)
        return ds
    return datasets.synthetic_geometry(
        info["name"], info.get("subset_size") or 1_000_000,
        info["dims"] or 96, info["metric"], scale=args.scale,
    )


def _clamp_n_lists(config, ds):
    """A scaled-down run keeps the conf's tuning grid but must respect the
    hard n_lists <= n constraint (a 50K-list deep-100M entry on a 1% smoke
    has more lists than rows) — clamp sub-sqrt-law and say so."""
    n_rows = ds.base.shape[0]
    cap = max(16, int(5 * n_rows**0.5))
    for a in config["algos"]:
        nl = a["build_param"].get("n_lists", 0)
        if nl > cap:
            print(f"clamped {a.get('label', a['name'])} n_lists "
                  f"{nl} -> {cap} (n={n_rows})", file=sys.stderr)
            a["build_param"]["n_lists"] = cap


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "compare":
        # regression gate: diff two bench records, exit nonzero on a
        # regression — ``python -m raft_tpu.bench compare --baseline X``
        return export.compare_main(argv[1:])
    if argv and argv[0] == "frontier":
        # QPS–recall frontier sweep → serialized FrontierModel (the
        # autotuner's measurement leg) — lazy import keeps the default
        # path free of the sweep machinery
        from raft_tpu.bench import frontier as frontier_mod

        return frontier_mod.frontier_main(argv[1:])
    ap = argparse.ArgumentParser("raft_tpu.bench")
    ap.add_argument("--dataset", default="sift-128-euclidean")
    ap.add_argument("--scale", type=float, default=0.01,
                    help="fraction of the standard dataset size to generate")
    ap.add_argument("--config", default="", help="JSON config path (this "
                    "repo's {algos: [...]} shape)")
    ap.add_argument("--conf", default="", help="reference-shaped per-dataset "
                    "conf (run/conf/*.json) — runs unmodified")
    ap.add_argument("--algo-yaml", default="", help="reference-shaped per-"
                    "algo tuning grid (run/conf/algos/*.yaml) — cartesian "
                    "expansion like run/__main__; combine with --group and "
                    "--datasets-yaml/--dataset")
    ap.add_argument("--group", default="base",
                    help="tuning group inside --algo-yaml (base/large/...)")
    ap.add_argument("--datasets-yaml", default="",
                    help="reference run/conf/datasets.yaml registry; "
                    "--dataset then names an entry in it")
    ap.add_argument("--data-dir", default="",
                    help="root for the conf's base_file/query_file paths")
    ap.add_argument("-k", type=int, default=0)
    ap.add_argument("--out", default="bench_results")
    ap.add_argument("--algorithms", default="",
                    help="comma-separated filter over config algos")
    args = ap.parse_args(argv)

    k = args.k or 10
    if args.algo_yaml:
        # reference YAML tuning-grid parity (the run/conf/algos/*.yaml
        # side of VERDICT r4 next #8): cartesian-expand the named group
        # against the dataset registry (or the built-in geometry table)
        from raft_tpu.bench import conf as conf_mod

        if args.datasets_yaml:
            registry = conf_mod.load_datasets_yaml(args.datasets_yaml)
            if args.dataset not in registry:
                print(f"{args.dataset!r} not in {args.datasets_yaml}; "
                      f"have {sorted(registry)}", file=sys.stderr)
                return 1
            info = registry[args.dataset]
        else:
            dims, metric = conf_mod._REF_DATASET_GEOMETRY.get(
                args.dataset, (0, "sqeuclidean"))
            info = {"name": args.dataset, "dims": dims, "metric": metric,
                    "subset_size": 0, "k": k,
                    "base_file": "", "query_file": ""}
        config = conf_mod.load_algo_yaml(
            args.algo_yaml, group=args.group, dataset_info=info)
        for note in config.pop("skipped", []):
            print(f"skipped: {note}", file=sys.stderr)
        if args.algorithms:
            # match the expanded label, the engine name, OR the yaml's own
            # algo name (the label's dot-prefix) — same acceptance as the
            # --conf path's algo_filter
            keep = set(args.algorithms.split(","))
            config["algos"] = [
                a for a in config["algos"]
                if a.get("label") in keep or a["name"] in keep
                or a.get("label", "").split(".")[0] in keep
            ]
        if not config["algos"]:
            print("grid contained no runnable entries", file=sys.stderr)
            return 1
        ds = _conf_dataset(info, args)
        _clamp_n_lists(config, ds)
    elif args.conf:
        # reference conf-file parity (VERDICT r4 next #8): translate the
        # upstream JSON (dataset section + per-algo tuning grids) and run it
        from raft_tpu.bench import conf as conf_mod

        algo_filter = set(args.algorithms.split(",")) if args.algorithms \
            else None
        info, config, skipped = conf_mod.load(args.conf,
                                              algo_filter=algo_filter)
        for note in skipped:
            print(f"skipped: {note}", file=sys.stderr)
        if not config["algos"]:
            print("conf contained no runnable algos", file=sys.stderr)
            return 1
        k = args.k or info["k"]
        ds = _conf_dataset(info, args)
        _clamp_n_lists(config, ds)
    else:
        config = (
            json.load(open(args.config)) if args.config else DEFAULT_CONFIG
        )
        if args.algorithms:
            keep = set(args.algorithms.split(","))
            config = {
                "algos": [a for a in config["algos"] if a["name"] in keep]
            }
        ds = datasets.synthetic(args.dataset, scale=args.scale)
    args.k = k
    if ds.gt_neighbors is None or ds.gt_neighbors.shape[1] < args.k:
        datasets.generate_groundtruth(ds, k=max(args.k, 100))
    results = runner.run_config(ds, config, k=args.k)

    os.makedirs(args.out, exist_ok=True)
    # conf-driven runs label artifacts with the CONF's dataset name, not
    # the unrelated --dataset default
    out_name = ds.name if (args.conf or args.algo_yaml) else args.dataset
    base = os.path.join(args.out, f"{out_name}")
    runner.save_results(results, base + ".json")
    export.to_csv(results, base + ".csv")
    # one comparable headline record per run: the best-QPS operating point
    # among the runs that achieved the sweep's best recall — the shape
    # ``compare`` diffs (schema-versioned, same envelope as bench.py legs)
    try:
        best_recall = max(r.recall for r in results)
        head = max(
            (r for r in results if r.recall >= best_recall - 0.02),
            key=lambda r: r.qps,
        )
        export.write_bench_record(
            {
                "metric": f"bench_{out_name}_k{args.k}",
                "value": round(head.qps, 1),
                "unit": "queries/s",
                "platform": "cpu"
                if _env.env_str("RAFT_TPU_PLATFORM") == "cpu"
                else None,
                "recall": round(head.recall, 4),
                "latency_ms": round(head.latency_ms, 3),
                "algo": head.algo,
                "search_param": head.search_param,
            },
            base + "_record.json",
        )
    except Exception as e:  # record is an artifact, not the result
        print(f"bench record not written: {e}", file=sys.stderr)
    try:
        plot.plot_results(results, base + ".png")
    except Exception as e:  # plotting is best-effort (headless variations)
        print(f"plot skipped: {e}", file=sys.stderr)
    for r in results:
        print(
            f"{r.algo:24s} recall={r.recall:.4f} qps={r.qps:10.1f} "
            f"latency={r.latency_ms:.3f}ms build={r.build_time_s:.1f}s "
            f"{r.search_param}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
