"""Dataset fetcher CLI (ref: raft-ann-bench ``get_dataset``,
python/raft-ann-bench/src/raft_ann_bench/get_dataset/__main__.py):
download a standard ANN benchmark dataset, convert it to the on-disk
layout the runner consumes (big-ann ``base.fbin``/``query.fbin``/
``groundtruth.*`` — see ``datasets.save``/``load``), and generate exact
groundtruth.

    python -m raft_tpu.bench.get_dataset --dataset sift-128-euclidean \
        --out-dir data/

Zero-egress environments: pass ``--synthetic`` to generate the
deterministic synthetic stand-in with the same geometry instead of
downloading (what the test suite and offline benches use).
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

#: ann-benchmarks HDF5 mirrors (ref: raft-ann-bench get_dataset URLs)
_ANN_BENCHMARKS_URL = "https://ann-benchmarks.com/{name}.hdf5"
#: big-ann-benchmarks binary sources for the large datasets: base file,
#: published disjoint query file, and the row count the "-100M" name promises
#: (the files themselves hold the full 1B rows — we slice while streaming).
_BIGANN_SOURCES = {
    "deep-100M": (
        "https://storage.yandexcloud.net/yandex-research/ann-datasets/DEEP/base.1B.fbin",
        "https://storage.yandexcloud.net/yandex-research/ann-datasets/DEEP/query.public.10K.fbin",
        100_000_000,
    ),
    "bigann-100M": (
        "https://dl.fbaipublicfiles.com/billion-scale-ann-benchmarks/bigann/base.1B.u8bin",
        "https://dl.fbaipublicfiles.com/billion-scale-ann-benchmarks/bigann/query.public.10K.u8bin",
        100_000_000,
    ),
}


def fetch(name: str, out_dir: str, *, synthetic: bool = False,
          scale: float = 1.0, k: int = 100) -> str:
    """Fetch (or synthesize) ``name`` into ``out_dir``; returns the dataset
    directory path consumable by ``datasets.load``."""
    from raft_tpu.bench import datasets

    dest = os.path.join(out_dir, name)
    if any(os.path.exists(os.path.join(dest, f"base.{e}"))
           for e in ("fbin", "u8bin", "i8bin")):
        print(f"{dest} already present", file=sys.stderr)
        return dest

    if synthetic:
        ds = datasets.synthetic(name, scale=scale)
        ds = datasets.generate_groundtruth(ds, k=k)
        datasets.save(ds, dest)
        return dest

    import urllib.error
    import urllib.request

    def download(url: str, tmp: str, *, rows: int = 0, itemsize: int = 0) -> str:
        """Fetch ``url`` into ``tmp``. With ``rows``, stream only the
        first ``rows`` vectors of a big-ann binary file (the 1B-row source
        files are sliceable prefixes — never transfer the other 90%) and
        rewrite the header row count to match."""
        try:
            print(f"downloading {url} ...", file=sys.stderr)
            if not rows:
                urllib.request.urlretrieve(url, tmp)  # nosec - benchmark data
                return tmp
            with urllib.request.urlopen(url) as resp:  # nosec - benchmark data
                header = resp.read(8)
                n_total, dim = (int(v) for v in np.frombuffer(header, np.int32))
                rows = min(rows, n_total)
                remaining = rows * dim * itemsize
                with open(tmp, "wb") as fh:
                    fh.write(np.asarray([rows, dim], np.int32).tobytes())
                    while remaining:
                        chunk = resp.read(min(remaining, 1 << 24))
                        if not chunk:
                            raise RuntimeError(
                                f"{url}: stream ended {remaining} bytes short"
                            )
                        fh.write(chunk)
                        remaining -= len(chunk)
        except (urllib.error.URLError, OSError) as e:
            raise RuntimeError(
                f"download failed ({e}); in an offline environment use "
                "--synthetic for the deterministic stand-in with the same "
                "geometry"
            ) from e
        return tmp

    os.makedirs(out_dir, exist_ok=True)
    tmps = []
    if name in _BIGANN_SOURCES:
        base_url, query_url, n_rows = _BIGANN_SOURCES[name]
        # dtype comes from the SOURCE extension — the temp file's
        # ".download" suffix would otherwise mis-infer u8bin as float32.
        dtype = datasets._DTYPES[base_url.rsplit(".", 1)[-1]]
        n_rows = max(1, int(n_rows * scale))
        tmps.append(download(
            base_url, os.path.join(out_dir, f"{name}.base.download"),
            rows=n_rows, itemsize=np.dtype(dtype).itemsize,
        ))
        # memmap the sliced prefix — groundtruth + save both stream it
        base = datasets.read_bin(tmps[0], dtype, mmap=True)
        tmps.append(download(query_url, os.path.join(out_dir, f"{name}.query.download")))
        queries = datasets.read_bin(tmps[1], dtype)
        ds = datasets.Dataset(name=name, base=base, queries=queries,
                              metric="sqeuclidean")
    else:
        url = _ANN_BENCHMARKS_URL.format(name=name)
        tmps.append(download(url, os.path.join(out_dir, f"{name}.download")))
        ds = datasets.load_hdf5(tmps[0], name=name)
    if ds.gt_neighbors is None:
        ds = datasets.generate_groundtruth(ds, k=k)
    datasets.save(ds, dest)
    for tmp in tmps:
        os.remove(tmp)
    return dest


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("raft_tpu.bench.get_dataset")
    ap.add_argument("--dataset", default="sift-128-euclidean")
    ap.add_argument("--out-dir", default="data")
    ap.add_argument("--synthetic", action="store_true",
                    help="generate the synthetic stand-in instead of "
                    "downloading (zero-egress environments)")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("-k", type=int, default=100)
    args = ap.parse_args(argv)
    dest = fetch(args.dataset, args.out_dir, synthetic=args.synthetic,
                 scale=args.scale, k=args.k)
    print(dest)
    return 0


if __name__ == "__main__":
    sys.exit(main())
