"""Dataset fetcher CLI (ref: raft-ann-bench ``get_dataset``,
python/raft-ann-bench/src/raft_ann_bench/get_dataset/__main__.py):
download a standard ANN benchmark dataset, convert it to the on-disk
layout the runner consumes (big-ann ``base.fbin``/``query.fbin``/
``groundtruth.*`` — see ``datasets.save``/``load``), and generate exact
groundtruth.

    python -m raft_tpu.bench.get_dataset --dataset sift-128-euclidean \
        --out-dir data/

Zero-egress environments: pass ``--synthetic`` to generate the
deterministic synthetic stand-in with the same geometry instead of
downloading (what the test suite and offline benches use).
"""

from __future__ import annotations

import argparse
import os
import sys

#: ann-benchmarks HDF5 mirrors (ref: raft-ann-bench get_dataset URLs)
_ANN_BENCHMARKS_URL = "https://ann-benchmarks.com/{name}.hdf5"
#: big-ann-benchmarks binary sources for the large datasets
_BIGANN_URLS = {
    "deep-100M": "https://storage.yandexcloud.net/yandex-research/ann-datasets/DEEP/base.1B.fbin",
    "bigann-100M": "https://dl.fbaipublicfiles.com/billion-scale-ann-benchmarks/bigann/base.1B.u8bin",
}


def fetch(name: str, out_dir: str, *, synthetic: bool = False,
          scale: float = 1.0, k: int = 100) -> str:
    """Fetch (or synthesize) ``name`` into ``out_dir``; returns the dataset
    directory path consumable by ``datasets.load``."""
    from raft_tpu.bench import datasets

    dest = os.path.join(out_dir, name)
    if os.path.exists(os.path.join(dest, "base.fbin")):
        print(f"{dest} already present", file=sys.stderr)
        return dest

    if synthetic:
        ds = datasets.synthetic(name, scale=scale)
        ds = datasets.generate_groundtruth(ds, k=k)
        datasets.save(ds, dest)
        return dest

    url = (
        _BIGANN_URLS[name]
        if name in _BIGANN_URLS
        else _ANN_BENCHMARKS_URL.format(name=name)
    )
    tmp = os.path.join(out_dir, f"{name}.download")
    os.makedirs(out_dir, exist_ok=True)
    import urllib.error
    import urllib.request

    try:
        print(f"downloading {url} ...", file=sys.stderr)
        urllib.request.urlretrieve(url, tmp)  # nosec - benchmark data fetch
    except (urllib.error.URLError, OSError) as e:
        raise RuntimeError(
            f"download failed ({e}); in an offline environment use "
            "--synthetic for the deterministic stand-in with the same "
            "geometry"
        ) from e
    if url.endswith(".hdf5"):
        ds = datasets.load_hdf5(tmp, name=name)
    else:
        base = datasets.read_bin(tmp)
        ds = datasets.Dataset(name=name, base=base, queries=base[:10_000],
                              metric="sqeuclidean")
    if ds.gt_neighbors is None:
        ds = datasets.generate_groundtruth(ds, k=k)
    datasets.save(ds, dest)
    os.remove(tmp)
    return dest


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("raft_tpu.bench.get_dataset")
    ap.add_argument("--dataset", default="sift-128-euclidean")
    ap.add_argument("--out-dir", default="data")
    ap.add_argument("--synthetic", action="store_true",
                    help="generate the synthetic stand-in instead of "
                    "downloading (zero-egress environments)")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("-k", type=int, default=100)
    args = ap.parse_args(argv)
    dest = fetch(args.dataset, args.out_dir, synthetic=args.synthetic,
                 scale=args.scale, k=args.k)
    print(dest)
    return 0


if __name__ == "__main__":
    sys.exit(main())
