"""Benchmark orchestrator: algorithm wrappers + QPS/latency/recall runner.

Reference: the abstract ANN interface ``cpp/bench/ann/src/common/
ann_types.hpp:79-157`` (build / set_search_param / search / save / load),
the gbench driver computing QPS, latency, GPU-time and Recall counters
(``cpp/bench/ann/src/common/benchmark.hpp:120-379``), and the Python
orchestrator that launches runs from JSON configs
(python/raft-ann-bench/src/raft_ann_bench/run/__main__.py:115-190).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu.bench import device_time
from raft_tpu.core.resources import Resources, ensure
from raft_tpu.bench.datasets import Dataset
from raft_tpu.stats import recall_at_k


class ANN:
    """Algorithm wrapper interface (ref: ann_types.hpp ANN<T>)."""

    name = "base"

    def __init__(self, metric: str, build_param: Dict[str, Any]):
        self.metric = metric
        self.build_param = build_param

    def build(self, dataset: np.ndarray) -> None:
        raise NotImplementedError

    def set_search_param(self, param: Dict[str, Any]) -> None:
        raise NotImplementedError

    def search(self, queries: jnp.ndarray, k: int):
        raise NotImplementedError

    def save(self, path: str) -> None:
        pass

    def load(self, path: str) -> None:
        pass


class BruteForceANN(ANN):
    name = "raft_tpu_brute_force"

    def build(self, dataset):
        from raft_tpu.neighbors import brute_force

        self._mod = brute_force
        self._index = brute_force.build(jnp.asarray(dataset), metric=self.metric)

    def set_search_param(self, param):
        pass

    def search(self, queries, k):
        return self._mod.search(self._index, queries, k)

    def save(self, path):
        self._mod.save(path, self._index)


class IvfFlatANN(ANN):
    name = "raft_tpu_ivf_flat"

    def build(self, dataset):
        from raft_tpu.neighbors import ivf_flat

        self._mod = ivf_flat
        params = ivf_flat.IndexParams(metric=self.metric, **self.build_param)
        self._index = ivf_flat.build(params, jnp.asarray(dataset))
        self._sp = ivf_flat.SearchParams()

    def set_search_param(self, param):
        from raft_tpu.neighbors import ivf_flat

        self._sp = ivf_flat.SearchParams(**param)

    def search(self, queries, k):
        return self._mod.search(self._sp, self._index, queries, k)

    def save(self, path):
        self._mod.save(path, self._index)


class IvfPqANN(ANN):
    name = "raft_tpu_ivf_pq"

    def build(self, dataset):
        from raft_tpu.neighbors import ivf_pq

        self._mod = ivf_pq
        self._refine_ratio = 1
        params = ivf_pq.IndexParams(metric=self.metric, **self.build_param)
        self._dataset = jnp.asarray(dataset)
        self._index = ivf_pq.build(params, self._dataset)
        self._sp = ivf_pq.SearchParams()

    def set_search_param(self, param):
        from raft_tpu.neighbors import ivf_pq

        param = dict(param)
        self._refine_ratio = int(param.pop("refine_ratio", 1))
        self._sp = ivf_pq.SearchParams(**param)

    def search(self, queries, k):
        from raft_tpu.neighbors import refine

        if self._refine_ratio > 1:
            _, cand = self._mod.search(
                self._sp, self._index, queries, k * self._refine_ratio
            )
            return refine(self._dataset, queries, cand, k, metric=self.metric)
        return self._mod.search(self._sp, self._index, queries, k)

    def save(self, path):
        self._mod.save(path, self._index)


#: single-slot CAGRA build cache: the bf16/VPQ bench variants share the
#: plain variant's graph (they differ only in the traversal dataset's
#: representation), so a frontier sweep pays the ~20-min 1M graph build
#: once instead of three times.  One slot keeps device-memory pinning
#: bounded (the dense index stays resident until a different key lands).
_CAGRA_BUILD_CACHE: dict = {}


class CagraANN(ANN):
    name = "raft_tpu_cagra"

    def build(self, dataset):
        from raft_tpu.neighbors import cagra

        self._mod = cagra
        bp = dict(self.build_param)
        # "compress": True benches the VPQ-compressed dataset variant
        # (decode-on-gather — the memory-lean CAGRA, ref cagra
        # index_params.compression)
        compress = bp.pop("compress", False)
        # "dataset_dtype": "bfloat16" stores the traversal dataset in bf16
        # — halves the hot loop's gather bytes (the reference's half-
        # precision dataset template, cagra_types.hpp:142).  The graph is
        # built (and cached) at full precision; the dtype only changes the
        # stored traversal rows, mirroring the reference's semantics.
        ds_dtype = bp.pop("dataset_dtype", None)
        params = cagra.IndexParams(metric=self.metric, **bp)
        ds = jnp.asarray(dataset)
        sample = np.asarray(dataset[: min(256, dataset.shape[0])])
        key = (dataset.shape, str(sample.dtype), hash(sample.tobytes()),
               self.metric, tuple(sorted(bp.items())))
        cached = _CAGRA_BUILD_CACHE.get(key)
        if cached is None:
            t0 = time.perf_counter()
            base = cagra.build(params, ds)
            jax.block_until_ready(base.graph)
            build_s = time.perf_counter() - t0
            _CAGRA_BUILD_CACHE.clear()
            _CAGRA_BUILD_CACHE[key] = (base, build_s)
            self._cache_hit = False
        else:
            base, build_s = cached
            self._cache_hit = True
        # the real (shared) graph-build cost: a cache hit must not report
        # ~0s build_time_s in frontier artifacts — ann-bench semantics are
        # true per-algo build measurement, and the variants share one build
        self.shared_build_s = build_s
        index = base
        if ds_dtype:
            index = cagra.Index(
                base.metric, base.dataset.astype(ds_dtype), base.graph,
                base.entry_centers, base.entry_ids,
            )
        if compress:
            index = cagra.compress(index)
        self._index = index
        self._sp = cagra.SearchParams()

    def set_search_param(self, param):
        from raft_tpu.neighbors import cagra

        self._sp = cagra.SearchParams(**param)

    def search(self, queries, k):
        return self._mod.search(self._sp, self._index, queries, k)

    def save(self, path):
        self._mod.save(path, self._index)


class CagraVpqANN(CagraANN):
    """CAGRA over a VPQ-compressed dataset (decode-on-gather) — the
    memory-lean variant benched as its own algorithm so frontier
    artifacts separate its pareto curve from dense CAGRA."""

    name = "raft_tpu_cagra_vpq"

    def build(self, dataset):
        self.build_param = {**self.build_param, "compress": True}
        super().build(dataset)


class CagraBf16ANN(CagraANN):
    """CAGRA over a bf16 traversal dataset — half the gather bytes in the
    bandwidth-bound beam search (the reference's half-precision dataset
    template, cagra_types.hpp:142)."""

    name = "raft_tpu_cagra_bf16"

    def build(self, dataset):
        self.build_param = {**self.build_param, "dataset_dtype": "bfloat16"}
        super().build(dataset)


class BallCoverANN(ANN):
    name = "raft_tpu_ball_cover"

    def build(self, dataset):
        from raft_tpu.neighbors import ball_cover

        self._mod = ball_cover
        self._index = ball_cover.build(
            jnp.asarray(dataset), metric=self.metric, **self.build_param
        )
        self._n_probes = 0

    def set_search_param(self, param):
        self._n_probes = int(param.get("n_probes", 0))

    def search(self, queries, k):
        return self._mod.knn_query(self._index, queries, k, n_probes=self._n_probes)


class NumpyExactANN(ANN):
    """Competitor baseline: pure-numpy exact kNN, no JAX/XLA anywhere
    (ref: the reference benches its algorithms against external
    competitors, cpp/bench/ann/src/{faiss,hnswlib,ggnn}/ — this is the
    honest host-CPU floor every accelerated algorithm must beat)."""

    name = "numpy_exact"

    def build(self, dataset):
        self._x = np.ascontiguousarray(dataset, np.float32)
        self._x2 = (self._x.astype(np.float64) ** 2).sum(-1)
        self._xn = np.sqrt(np.maximum(self._x2, 1e-30))

    def set_search_param(self, param):
        self._tile = int(param.get("tile", 2048))

    def search(self, queries, k):
        q = np.ascontiguousarray(queries, np.float32)
        vals = np.empty((q.shape[0], k), np.float32)
        ids = np.empty((q.shape[0], k), np.int32)
        for s in range(0, q.shape[0], self._tile):
            qt = q[s : s + self._tile]
            if self.metric == "inner_product":
                d = -(qt @ self._x.T)
            elif self.metric == "cosine":
                qn = np.sqrt(np.maximum((qt.astype(np.float64) ** 2)
                                        .sum(-1), 1e-30))
                d = 1.0 - (qt @ self._x.T) / (qn[:, None] * self._xn[None, :])
            else:
                d = self._x2[None, :] - 2.0 * (qt @ self._x.T)
                # +‖q‖² completes the true squared-L2 value: ranks don't
                # need it, but frontier artifacts compare distance values
                # across algorithms
                d += (qt.astype(np.float64) ** 2).sum(-1)[:, None]
            part = np.argpartition(d, k - 1, axis=1)[:, :k]
            pv = np.take_along_axis(d, part, axis=1)
            order = np.argsort(pv, axis=1)
            ids[s : s + self._tile] = np.take_along_axis(part, order, axis=1)
            vals[s : s + self._tile] = np.take_along_axis(pv, order, axis=1)
        return vals, ids


class SklearnANN(ANN):
    """External-library comparator: scikit-learn NearestNeighbors
    (kd-tree / ball-tree / brute). The environment ships no ANN library
    (faiss/hnswlib need a pip install this image forbids), so sklearn's
    spatial trees are the independent third-party implementation that
    keeps 'competitive' claims falsifiable (ref: the reference benches
    against external libraries, cpp/bench/ann/src/{faiss,hnswlib}/).
    Exact search — its recall is 1.0 by construction; the comparison is
    throughput."""

    name = "sklearn"

    def build(self, dataset):
        from sklearn.neighbors import NearestNeighbors

        if self.metric == "inner_product":
            # no sklearn tree searches unnormalized MIP — refusing keeps
            # every 'sklearn'-labeled row a real third-party measurement
            # (numpy_exact is the IP floor)
            raise ValueError(
                "sklearn comparator has no inner_product mode; use "
                "numpy_exact for the IP floor"
            )
        self._x = np.ascontiguousarray(dataset, np.float32)
        if self.metric == "cosine":
            # cosine ranks == euclidean ranks on normalized vectors, so
            # the tree still does the searching; values convert below
            norms = np.sqrt((self._x.astype(np.float64) ** 2).sum(1))
            self._fit = self._x / np.maximum(norms, 1e-30)[:, None]
        else:
            self._fit = self._x
        self._algorithm = self.build_param.get("algorithm", "ball_tree")
        self._jobs = 1
        self._nn = None

    def _ensure_nn(self):
        from sklearn.neighbors import NearestNeighbors

        if self._nn is None:
            self._nn = NearestNeighbors(
                algorithm=self._algorithm, metric="euclidean",
                n_jobs=self._jobs,
            )
            self._nn.fit(self._fit)

    def set_search_param(self, param):
        jobs = int(param.get("n_jobs", 1))
        if jobs != self._jobs:
            self._jobs = jobs
            self._nn = None  # refit with the requested parallelism

    def search(self, queries, k):
        self._ensure_nn()
        q = np.ascontiguousarray(queries, np.float32)
        if self.metric == "cosine":
            qn = np.sqrt((q.astype(np.float64) ** 2).sum(1))
            q = (q / np.maximum(qn, 1e-30)[:, None]).astype(np.float32)
        dist, ids = self._nn.kneighbors(q, n_neighbors=k)
        if self.metric == "cosine":
            # ‖a−b‖² = 2 − 2cos on unit vectors ⇒ cosine distance = d²/2
            vals = (dist ** 2) / 2.0
        elif self.metric == "sqeuclidean":
            vals = dist ** 2
        else:
            vals = dist
        return vals.astype(np.float32), ids.astype(np.int32)


class HnswANN(ANN):
    """hnswlib-format comparator: the graph is built here, exported through
    the hnswlib binary layout, and searched either by real hnswlib (when
    installed) or by the in-repo loader+search over the same file
    (ref: cpp/bench/ann/src/hnswlib/ + neighbors/hnsw.hpp wrapper)."""

    name = "hnswlib_format"

    def _export(self, dataset):
        """Build the CAGRA graph and write the hnswlib interchange file —
        the part shared by every engine that searches the exported file."""
        import tempfile

        from raft_tpu.neighbors import cagra, hnsw

        self._hnsw = hnsw
        self._dim = dataset.shape[1]
        # entry_points=0: the hnswlib layout stores only dataset+graph, so
        # building cagra's entry table here would be discarded work
        params = cagra.IndexParams(
            metric=self.metric, **{"entry_points": 0, **self.build_param}
        )
        built = cagra.build(params, jnp.asarray(dataset))
        # round-trip through the binary format so the comparator exercises
        # the interchange layout, not the in-memory index
        fd, self._path = tempfile.mkstemp(suffix=".hnsw")
        os.close(fd)
        hnsw.serialize_to_hnswlib(self._path, built)

    def build(self, dataset):
        self._export(dataset)
        hnsw = self._hnsw
        try:  # real hnswlib when available; its absence is the only silent
            # fallback — a broken load of a present hnswlib must surface,
            # not quietly benchmark the wrong engine under this label
            import hnswlib  # type: ignore
        except ImportError:
            hnswlib = None
        if hnswlib is not None:
            space = "ip" if self.metric == "inner_product" else "l2"
            self._lib_index = hnswlib.Index(space=space, dim=self._dim)
            self._lib_index.load_index(self._path)
        else:
            self._lib_index = None
            self._index = hnsw.load(self._path, self._dim, metric=self.metric)
        self._ef = 64

    def __del__(self):
        path = getattr(self, "_path", None)
        if path and os.path.exists(path):
            try:
                os.remove(path)
            except OSError:
                pass

    def set_search_param(self, param):
        self._ef = int(param.get("ef", 64))
        if self._lib_index is not None:
            self._lib_index.set_ef(self._ef)

    def search(self, queries, k):
        if self._lib_index is not None:
            ids, dists = self._lib_index.knn_query(np.asarray(queries), k=k)
            return dists.astype(np.float32), ids.astype(np.int32)
        return self._hnsw.search(self._index, queries, k, ef=self._ef)

    def save(self, path):
        import shutil

        shutil.copy(self._path, path)


class HnswNativeANN(HnswANN):
    """Native-engine variant of ``hnswlib_format``: the exported file is
    searched by the from-scratch C++ HNSW engine (cpp/src/hnsw.cc — greedy
    upper-level descent + ef-bounded best-first, threaded over queries),
    the same role hnswlib's C++ plays in the reference's harness
    (cpp/bench/ann/src/hnswlib/hnswlib_wrapper.h). Pure host CPU — no JAX
    in the search path — so it is a genuinely separate codepath from every
    raft_tpu_* algorithm."""

    name = "hnsw_native"

    def build(self, dataset):
        self._export(dataset)  # graph + interchange file only — no beam/
        # hnswlib engine load whose work this class would discard
        from raft_tpu.neighbors import hnsw

        self._lib_index = None
        self._native = hnsw.load_native(self._path, self._dim)
        self._threads = 0
        self._ef = 64
        self._n_seeds = 1

    def set_search_param(self, param):
        super().set_search_param(param)
        self._threads = int(param.get("n_threads", 0))
        self._n_seeds = int(param.get("n_seeds", 1))

    def search(self, queries, k):
        d, ids = self._native.search(
            np.asarray(queries, np.float32), k, ef=self._ef,
            metric=self.metric, n_seeds=self._n_seeds,
            n_threads=self._threads,
        )
        return d, ids.astype(np.int32)


class _NativeANN(ANN):
    """Shared plumbing for the C-ABI engine competitors (cpp/src/
    ann_index.cc): threaded host C++ with no JAX in build or search —
    like ``hnsw_native``, genuinely separate codepaths playing the
    external-CPU-library role faiss-CPU plays in the reference harness."""

    def set_search_param(self, param):
        self._n_probes = int(param.get("n_probes", 32))
        self._itopk = int(param.get("itopk_size", 64))

    def search(self, queries, k):
        return self._index.search(np.asarray(queries, np.float32), k,
                                  n_probes=self._n_probes, itopk=self._itopk)

    def save(self, path):
        self._index.save(path)


class NativeIvfFlatANN(_NativeANN):
    name = "native_ivf_flat"

    def build(self, dataset):
        from raft_tpu.core import native

        x = np.asarray(dataset, np.float32)
        self._index = native.NativeAnnIndex.ivf_flat(
            x, n_lists=int(self.build_param.get("n_lists", 256)),
            metric=self.metric,
            kmeans_iters=int(self.build_param.get("kmeans_n_iters", 10)))
        self.set_search_param({})


def _divisor_pq_dim(dim: int, want: int) -> int:
    """Largest divisor of ``dim`` that is <= max(1, want) — the native
    engine requires dim % pq_dim == 0 (the JAX engine pads instead)."""
    want = max(1, min(want, dim))
    for cand in range(want, 0, -1):
        if dim % cand == 0:
            return cand
    return 1


class NativeIvfPqANN(_NativeANN):
    """C-ABI IVF-PQ (ADC LUT scan) + threaded exact host refine — the
    reference's classic CPU recipe, fully outside JAX."""

    name = "native_ivf_pq"

    def build(self, dataset):
        from raft_tpu.core import native

        self._x = np.asarray(dataset, np.float32)
        dim = self._x.shape[1]
        self._index = native.NativeAnnIndex.ivf_pq(
            self._x, n_lists=int(self.build_param.get("n_lists", 256)),
            pq_dim=_divisor_pq_dim(
                dim, int(self.build_param.get("pq_dim", dim // 4))),
            metric=self.metric,
            kmeans_iters=int(self.build_param.get("kmeans_n_iters", 10)))
        self.set_search_param({})

    def set_search_param(self, param):
        super().set_search_param(param)
        self._refine_ratio = int(param.get("refine_ratio", 4))

    def search(self, queries, k):
        from raft_tpu.core import native

        q = np.asarray(queries, np.float32)
        _, cand = self._index.search(q, k * self._refine_ratio,
                                     n_probes=self._n_probes)
        return native.refine_host(self._x, q, cand, k, metric=self.metric)


class NativeCagraANN(_NativeANN):
    name = "native_cagra"

    def build(self, dataset):
        from raft_tpu.core import native

        self._index = native.NativeAnnIndex.cagra(
            np.asarray(dataset, np.float32),
            graph_degree=int(self.build_param.get("graph_degree", 32)),
            metric=self.metric)
        self.set_search_param({})


ALGORITHMS = {
    a.name: a
    for a in (
        BruteForceANN, IvfFlatANN, IvfPqANN, CagraANN, CagraVpqANN,
        CagraBf16ANN, BallCoverANN, NumpyExactANN, SklearnANN, HnswANN,
        HnswNativeANN, NativeIvfFlatANN, NativeIvfPqANN, NativeCagraANN,
    )
}


@dataclass
class RunResult:
    """One (algo, build_param, search_param) measurement — the counters the
    reference's gbench driver reports (benchmark.hpp:330-379)."""

    algo: str
    dataset: str
    k: int
    build_param: Dict[str, Any]
    search_param: Dict[str, Any]
    build_time_s: float
    qps: float
    latency_ms: float
    recall: float
    end_to_end_s: float
    #: device-plane busy time for one search batch and the QPS it implies
    #: (the reference's CUDA-event GPU time, benchmark.hpp:165,330-333);
    #: None on host-only backends — never faked with wall time
    device_time_s: Optional[float] = None
    device_qps: Optional[float] = None

    def to_dict(self):
        return {
            "algo": self.algo, "dataset": self.dataset, "k": self.k,
            "build_param": self.build_param, "search_param": self.search_param,
            "build_time_s": self.build_time_s, "qps": self.qps,
            "latency_ms": self.latency_ms, "recall": self.recall,
            "end_to_end_s": self.end_to_end_s,
            "device_time_s": self.device_time_s,
            "device_qps": self.device_qps,
        }


def run_case(
    ds: Dataset,
    algo_name: str,
    build_param: Dict[str, Any],
    search_params: List[Dict[str, Any]],
    *,
    k: int = 10,
    warmup: int = 1,
    iters: int = 3,
    res: Optional[Resources] = None,
) -> List[RunResult]:
    """Build once, sweep search params (ref: run/__main__.py one executable
    invocation per build config with a search-param grid)."""
    if ds.gt_neighbors is None:
        raise ValueError("dataset has no groundtruth; run generate_groundtruth")
    res = ensure(res)
    cls = ALGORITHMS[algo_name]
    algo = cls(ds.metric, build_param)
    t0 = time.perf_counter()
    algo.build(ds.base)
    jax.block_until_ready(getattr(algo, "_index", jnp.zeros(())))
    build_time = time.perf_counter() - t0
    # an algo that shares a cached build reports the real build cost: on a
    # cache hit the wall time covers only the variant extras (dtype cast /
    # VPQ compress), so add the shared graph-build cost back; on a miss
    # the wall time already includes it
    if getattr(algo, "_cache_hit", False):
        build_time += getattr(algo, "shared_build_s", 0.0)

    queries = jnp.asarray(ds.queries)
    nq = ds.queries.shape[0]
    out = []
    for sp in search_params:
        algo.set_search_param(sp)
        for _ in range(warmup):
            jax.block_until_ready(algo.search(queries, k))
        t0 = time.perf_counter()
        for _ in range(iters):
            v, i = algo.search(queries, k)
        jax.block_until_ready((v, i))
        dt = (time.perf_counter() - t0) / iters
        rec = recall_at_k(np.asarray(i), ds.gt_neighbors[:, :k])
        # device-side time for one batch (None off-accelerator)
        dev_s = device_time.measure_device_time(
            lambda qq: algo.search(qq, k), queries
        )
        out.append(
            RunResult(
                algo=algo_name, dataset=ds.name, k=k,
                build_param=build_param, search_param=sp,
                build_time_s=build_time,
                qps=nq / dt,
                latency_ms=dt / nq * 1e3,
                recall=rec,
                end_to_end_s=dt,
                device_time_s=dev_s,
                device_qps=None if not dev_s else nq / dev_s,
            )
        )
    return out


def run_config(
    ds: Dataset, config: Dict[str, Any], *, k: int = 10,
    res: Optional[Resources] = None,
) -> List[RunResult]:
    """Execute a JSON config shaped like the reference's run/conf files:
    {"algos": [{"name": ..., "build_param": {...},
                "search_params": [{...}, ...]}, ...]}."""
    results = []
    for spec in config["algos"]:
        rs = run_case(
            ds, spec["name"], spec.get("build_param", {}),
            spec.get("search_params", [{}]), k=k, res=res,
        )
        # conf-translated entries carry the upstream entry name (e.g.
        # "raft_ivf_pq.d96b5n50K"); record it so several entries mapping
        # to one engine stay distinguishable in artifacts
        label = spec.get("label")
        if label:
            for r in rs:
                r.algo = label
        results.extend(rs)
    return results


def save_results(results: List[RunResult], path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as fh:
        json.dump([r.to_dict() for r in results], fh, indent=2)
