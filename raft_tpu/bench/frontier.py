"""Measured QPS–recall frontier sweep → serialized :class:`FrontierModel`.

Promoted from ``benchmarks/frontier.py`` (now a thin shim over this
module) and extended into the closed-loop autotuner's measurement leg:

- sweeps every algorithm's effort grid on a synthetic-or-real
  DEEP-geometry dataset at configurable scale (``--n``), per-algo
  checkpoint/resume included — a 100M sweep survives a mid-run death;
- optionally builds the four serve backends **shard-parallel** via
  :func:`raft_tpu.serve.build.build_sharded` (``--sharded``), the same
  pod-scale path the paged index store feeds, so the frontier can be
  measured at sizes a single device cannot hold;
- pareto-filters each serve backend's points and emits a
  schema-versioned :class:`~raft_tpu.obs.autotune.FrontierModel`
  document — the file ``RAFT_TPU_FRONTIER_PATH`` points the serving
  :class:`~raft_tpu.obs.autotune.Autotuner` at — plus the standard
  enveloped bench record for ``bench compare``.

    python -m raft_tpu.bench frontier --n 100000 --platform cpu

Writes ``benchmarks/frontier_<platform>.json`` (+ ``.png``) for the
human sweep artifact and ``--out`` (default
``benchmarks/frontier_model_<platform>.json``) for the serve-time model.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu.obs.autotune import FrontierModel, FrontierPoint

#: bench-harness algo name → serve backend tag: the FrontierModel key the
#: serving Autotuner resolves through ``EffortSpec.backend``.  Comparator
#: algos (numpy_exact, hnswlib, ...) stay in the sweep artifact but never
#: enter the model — the autotuner can only actuate the serve backends.
SERVE_BACKENDS = {
    "raft_tpu_brute_force": "brute_force",
    "raft_tpu_ivf_flat": "ivf_flat",
    "raft_tpu_ivf_pq": "ivf_pq",
    "raft_tpu_cagra": "cagra",
}


def default_grids(
    n: int, dim: int, metric: str, *, comparators: bool = True
) -> List[Tuple[str, Dict[str, Any], List[Dict[str, Any]]]]:
    """The sweep grid: ``(algo, build_param, effort points)`` per entry.

    The raft_tpu entries sweep exactly the knobs the serve-side
    ``EffortSpec`` actuates (n_probes / refine_ratio / itopk_size /
    search_width), so every measured point is a point the autotuner can
    actually select.
    """
    grids: List[Tuple[str, Dict[str, Any], List[Dict[str, Any]]]] = [
        ("raft_tpu_brute_force", {}, [{}]),
        (
            "raft_tpu_ivf_flat",
            {"n_lists": max(64, n // 500)},
            [{"n_probes": p} for p in (4, 8, 16, 32, 64)],
        ),
        (
            # pq_dim = d/2 (the reference's sift-1M grid region) — the
            # auto d/4 is too coarse past ~64 dims for recall≥0.9 at k=10
            "raft_tpu_ivf_pq",
            {"n_lists": max(64, n // 500), "pq_dim": dim // 2},
            [{"n_probes": p} for p in (4, 8, 16, 32, 64)]
            + [{"n_probes": p, "refine_ratio": r}
               for p in (8, 16, 32) for r in (2, 4)],
        ),
        (
            # deg-64 graph + entry-point-seeded w=1 walks — the winning
            # region from the round-4 sweep (see ROUND4_NOTES)
            "raft_tpu_cagra",
            {"graph_degree": 64, "intermediate_graph_degree": 128},
            [
                {"itopk_size": t, "search_width": 1, "max_iterations": mi,
                 "num_entry_centers": s}
                for t in (16, 32)
                for mi in (3, 4, 6, 8)
                for s in (8, 16)
            ]
            + [{"itopk_size": 64, "search_width": 1},
               {"itopk_size": 64, "search_width": 4}],
        ),
    ]
    if comparators:
        grids.insert(0, ("numpy_exact", {}, [{}]))
        grids.extend([
            (
                # half-the-gather-bytes CAGRA: bf16 traversal dataset
                "raft_tpu_cagra_bf16",
                {"graph_degree": 64, "intermediate_graph_degree": 128},
                [
                    {"itopk_size": t, "search_width": 1, "max_iterations": mi,
                     "num_entry_centers": 16}
                    for t in (16, 32) for mi in (4, 6, 8)
                ],
            ),
            (
                # memory-lean CAGRA: VPQ-compressed, decode-on-gather
                "raft_tpu_cagra_vpq",
                {"graph_degree": 64, "intermediate_graph_degree": 128},
                [
                    {"itopk_size": t, "search_width": 1, "max_iterations": mi,
                     "num_entry_centers": 16}
                    for t in (16, 32) for mi in (4, 8)
                ],
            ),
            ("hnswlib_format", {"graph_degree": 32},
             [{"ef": e} for e in (32, 64, 128)]),
            # same exported file, searched by the native C++ HNSW engine
            ("hnsw_native", {"graph_degree": 32},
             [{"ef": 64, "n_seeds": 1}, {"ef": 128, "n_seeds": 1},
              {"ef": 128, "n_seeds": 128}, {"ef": 256, "n_seeds": 256}]),
        ])
        if metric != "inner_product":
            # sklearn spatial trees refuse unnormalized MIP
            grids.insert(1, ("sklearn", {"algorithm": "ball_tree"}, [{}]))
    return grids


def make_dataset(name: str, n: int, *, n_queries: int, k: int,
                 dim: int = 0, metric: str = ""):
    """Synthetic-or-registered dataset at ``n`` rows with groundtruth.

    Known names scale the registered geometry (``datasets.synthetic``);
    unknown names fall back to explicit DEEP-like geometry (``--dim`` /
    ``--metric``, defaulting to deep's 96-dim inner product).
    """
    from raft_tpu.bench import datasets
    from raft_tpu.bench.datasets import _SYNTH_SHAPES

    if name in _SYNTH_SHAPES:
        full_n = _SYNTH_SHAPES[name][0]
        ds = datasets.synthetic(name, scale=n / full_n, n_queries=n_queries)
    else:
        ds = datasets.synthetic_geometry(
            name, n, dim or 96, metric or "inner_product",
            n_queries=n_queries,
        )
    return datasets.generate_groundtruth(ds, k=k)


# -- the sweep -----------------------------------------------------------


def sweep(ds, grids, *, k: int, checkpoint_path: str,
          warmup: int = 1, iters: int = 3) -> List[Any]:
    """Run every grid entry with per-algo checkpoint/resume.

    A tunnel death mid-sweep must not lose the completed algos'
    measurements (a 1M sweep is ~10 min/algo on chip): each finished
    algo appends to ``<checkpoint_path>`` and a restart resumes from it,
    re-running only what's missing.  A backend-unavailable failure keeps
    the algo un-done and aborts (``SystemExit``) so the resume retries
    it instead of failing every remaining algo against a dead chip.
    """
    from raft_tpu.bench import runner

    n = int(ds.base.shape[0])
    done_algos: set = set()
    results: List[Any] = []
    if os.path.exists(checkpoint_path):
        try:
            with open(checkpoint_path) as fh:
                part = json.load(fh)
            # dataset is part of the signature: a leftover partial from a
            # different dataset with matching n/k must not merge stale
            # measurements into this artifact
            if (part.get("n"), part.get("k"),
                    part.get("dataset")) == (n, k, ds.name):
                done_algos = set(part["done_algos"])
                results = [runner.RunResult(**d) for d in part["results"]]
                print(f"resuming from {checkpoint_path}: "
                      f"{sorted(done_algos)} done")
        except Exception as e:
            print(f"ignoring unreadable partial ({e})")

    def checkpoint() -> None:
        with open(checkpoint_path, "w") as fh:
            json.dump(
                {"n": n, "k": k, "dataset": ds.name,
                 "done_algos": sorted(done_algos),
                 "results": [r.to_dict() for r in results]}, fh,
            )

    for name, build_param, search_params in grids:
        if name in done_algos:
            continue
        t0 = time.time()
        try:
            rs = runner.run_case(
                ds, name, build_param, search_params, k=k,
                warmup=warmup, iters=iters,
            )
        except Exception as e:  # record the failure, keep the sweep going
            print(f"{name}: FAILED ({e})")
            if "unavailable" in str(e).lower():
                checkpoint()
                print("backend unavailable — aborting; checkpoint kept")
                raise SystemExit(1)
            done_algos.add(name)
            checkpoint()
            continue
        results.extend(rs)
        done_algos.add(name)
        checkpoint()
        good = [r for r in rs if r.recall >= 0.9] or rs
        best = max(good, key=lambda r: r.qps)
        print(
            f"{name}: {len(rs)} points in {time.time()-t0:.0f}s; "
            f"best{'@recall≥0.9' if good is not rs else ' (no point ≥0.9)'}: "
            f"{best.qps:.0f} qps @ {best.recall:.3f}"
        )
    return results


def sweep_sharded(ds, *, kinds: Sequence[str], k: int,
                  n_devices: Optional[int] = None,
                  warmup: int = 1, iters: int = 3) -> List[Any]:
    """Shard-parallel sweep: build each serve backend once via
    :func:`~raft_tpu.serve.build.build_sharded` (row-sharded training
    over the local mesh — the path a 100M paged-store corpus feeds),
    then sweep the effort knobs the :class:`ShardedIndex` reads per
    dispatch.  Only the serve backends run here; comparators have no
    sharded leg."""
    import dataclasses

    import jax

    from raft_tpu.bench import device_time, runner
    from raft_tpu.serve.build import build_sharded

    queries = np.asarray(ds.queries, np.float32)
    nq = queries.shape[0]
    results: List[Any] = []
    for algo in kinds:
        kind = SERVE_BACKENDS[algo]
        t0 = time.perf_counter()
        sidx = build_sharded(kind, np.asarray(ds.base, np.float32),
                             n_devices=n_devices, metric=ds.metric)
        build_s = time.perf_counter() - t0
        base_sp = sidx.search_params
        if kind == "brute_force":
            grid: List[Dict[str, Any]] = [{}]
        elif kind == "cagra":
            grid = [{"itopk_size": t} for t in (16, 32, 64)]
        else:
            grid = [{"n_probes": p} for p in (4, 8, 16, 32, 64)]
        for effort in grid:
            # the ShardedIndex reads search_params per dispatch (host
            # value), so swapping it between points costs one cached
            # executable per distinct value — exactly the serving shape
            if effort and base_sp is not None:
                sidx.search_params = dataclasses.replace(base_sp, **effort)
            for _ in range(warmup):
                jax.block_until_ready(sidx.search(queries, k))
            t0 = time.perf_counter()
            for _ in range(iters):
                d, i = sidx.search(queries, k)
            jax.block_until_ready((d, i))
            dt = (time.perf_counter() - t0) / iters
            rec = runner.recall_at_k(np.asarray(i), ds.gt_neighbors[:, :k])
            dev_s = device_time.measure_device_time(
                lambda qq: sidx.search(qq, k), queries
            )
            results.append(runner.RunResult(
                algo=algo, dataset=ds.name, k=k,
                build_param={"sharded": sidx.n_shards},
                search_param=dict(effort),
                build_time_s=build_s, qps=nq / dt,
                latency_ms=dt / nq * 1e3, recall=rec, end_to_end_s=dt,
                device_time_s=dev_s,
                device_qps=None if not dev_s else nq / dev_s,
            ))
        if base_sp is not None:
            sidx.search_params = base_sp
        best = max(results[-len(grid):], key=lambda r: r.qps)
        print(f"{algo} (sharded x{sidx.n_shards}): {len(grid)} points; "
              f"best {best.qps:.0f} qps @ {best.recall:.3f}")
    return results


# -- the model -----------------------------------------------------------


def frontier_model(results, *, n_queries: int,
                   meta: Optional[Dict[str, Any]] = None) -> FrontierModel:
    """Fold sweep results into a pareto-filtered :class:`FrontierModel`.

    Only serve-backend points enter (the autotuner can't actuate a
    comparator); ``device_s_per_query`` comes from the measured
    device-plane batch time (None off-accelerator, never faked)."""
    model = FrontierModel(meta=dict(meta or {}))
    for r in results:
        backend = SERVE_BACKENDS.get(r.algo)
        if backend is None:
            continue
        model.add(backend, FrontierPoint(
            effort=dict(r.search_param),
            qps=float(r.qps),
            recall=float(r.recall),
            device_s_per_query=(
                None if not r.device_time_s
                else float(r.device_time_s) / max(1, n_queries)
            ),
        ))
    model.pareto_filter()
    return model


# -- CLI -----------------------------------------------------------------


def frontier_main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        "raft_tpu.bench frontier",
        description="measured QPS–recall frontier sweep → FrontierModel",
    )
    ap.add_argument("--n", type=int, default=100_000)
    ap.add_argument("--dataset", default="deep-image-96-inner",
                    help="synthetic stand-in geometry (see bench.datasets); "
                    "unknown names use --dim/--metric DEEP-like geometry")
    ap.add_argument("--dim", type=int, default=0)
    ap.add_argument("--metric", default="")
    ap.add_argument("--queries", type=int, default=1000)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--platform", default="",
                    help="e.g. cpu to force a backend")
    ap.add_argument("--algos", default="",
                    help="comma-filter, e.g. numpy_exact,raft_tpu_ivf_pq")
    ap.add_argument("--no-comparators", action="store_true",
                    help="serve backends only (the autotuner's model leg)")
    ap.add_argument("--sharded", type=int, default=0, metavar="N",
                    help="build the serve backends shard-parallel over N "
                    "devices (0: single-device runner sweep)")
    ap.add_argument("--warmup", type=int, default=1)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--sweep-out", default="",
                    help="human sweep artifact (default benchmarks/"
                    "frontier_<platform>.json)")
    ap.add_argument("--out", default="",
                    help="FrontierModel path (default benchmarks/"
                    "frontier_model_<platform>.json) — point "
                    "RAFT_TPU_FRONTIER_PATH here")
    args = ap.parse_args(list(argv) if argv is not None else None)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    platform = jax.devices()[0].platform

    from raft_tpu.bench import export, plot

    ds = make_dataset(args.dataset, args.n, n_queries=args.queries,
                      k=args.k, dim=args.dim, metric=args.metric)
    n, dim = int(ds.base.shape[0]), int(ds.base.shape[1])

    bench_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "benchmarks",
    )
    sweep_out = args.sweep_out or os.path.join(
        bench_dir, f"frontier_{platform}.json")
    model_out = args.out or os.path.join(
        bench_dir, f"frontier_model_{platform}.json")

    if args.sharded:
        kinds = [a for a in SERVE_BACKENDS
                 if not args.algos or a in set(args.algos.split(","))]
        results = sweep_sharded(
            ds, kinds=kinds, k=args.k, n_devices=args.sharded,
            warmup=args.warmup, iters=args.iters,
        )
    else:
        grids = default_grids(
            n, dim, ds.metric, comparators=not args.no_comparators)
        if args.algos:
            keep = set(args.algos.split(","))
            grids = [g for g in grids if g[0] in keep]
        results = sweep(
            ds, grids, k=args.k, checkpoint_path=sweep_out + ".partial",
            warmup=args.warmup, iters=args.iters,
        )

    # per-algo build cost, first-class: build time gates alongside the
    # QPS pareto — search wins don't excuse uncompetitive builds.
    build_seconds: Dict[str, float] = {}
    for r in results:
        build_seconds[r.algo] = max(
            build_seconds.get(r.algo, 0.0), r.build_time_s)
    for a, bs in sorted(build_seconds.items()):
        print(f"build_s {a}: {bs:.1f}")

    doc = {
        "platform": platform,
        "n": n,
        "dim": dim,
        "n_queries": int(ds.queries.shape[0]),
        "k": args.k,
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "build_seconds": build_seconds,
        "frontiers": dict(plot.group_frontiers(results)),
        "results": [r.to_dict() for r in results],
    }
    os.makedirs(os.path.dirname(sweep_out) or ".", exist_ok=True)
    with open(sweep_out, "w") as fh:
        json.dump(doc, fh, indent=2)
    part_path = sweep_out + ".partial"
    if os.path.exists(part_path):
        os.remove(part_path)
    print("wrote", sweep_out)

    meta = {
        "dataset": ds.name, "n": n, "dim": dim,
        "n_queries": int(ds.queries.shape[0]), "k": args.k,
        "platform": platform, "metric": ds.metric,
        "sharded": int(args.sharded),
    }
    model = frontier_model(
        results, n_queries=int(ds.queries.shape[0]), meta=meta)
    model.save(model_out)
    print("wrote", model_out,
          f"({sum(len(p) for p in model.points.values())} pareto points "
          f"across {len(model.points)} backends)")

    # the comparable headline for ``bench compare``: best serve-backend
    # QPS at recall ≥ 0.9 (falls back to the overall best when nothing
    # clears it — tiny smoke sweeps)
    serve_pts = [r for r in results if r.algo in SERVE_BACKENDS]
    if serve_pts:
        good = [r for r in serve_pts if r.recall >= 0.9] or serve_pts
        head = max(good, key=lambda r: r.qps)
        export.write_bench_record({
            "metric": f"frontier_{ds.name}_k{args.k}",
            "value": round(head.qps, 1),
            "unit": "queries/s",
            "platform": platform if platform == "cpu" else None,
            "recall": round(head.recall, 4),
            "algo": head.algo,
            "search_param": head.search_param,
            "frontier": model.to_dict(),
        })

    try:
        plot.plot_results(results, sweep_out.replace(".json", ".png"),
                          title=f"recall/QPS frontier ({platform}, n={n})")
        print("wrote", sweep_out.replace(".json", ".png"))
    except Exception as e:
        print("plot skipped:", e)
    return 0


if __name__ == "__main__":
    sys.exit(frontier_main())
